"""Request DB + process executor for the API server.

Reference: sky/server/requests/executor.py (1208 LoC) — requests
persisted in a DB, LONG/SHORT queues, a process pool of disposable
workers, per-request log files, env/config isolation, kill-on-cancel.

This build: every request is one forked process (cancellation = kill
process group; memory returned to the OS when it exits — the
reference's BurstableExecutor "disposable worker" behavior), with a
semaphore per queue bounding concurrency.
"""
from __future__ import annotations

import enum
import functools
import importlib
import json
import multiprocessing
import os
import pickle
import signal
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.utils import db_utils
from skypilot_tpu.utils import subprocess_utils

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS requests (
    request_id TEXT PRIMARY KEY,
    name TEXT,
    entrypoint TEXT,
    payload TEXT,
    status TEXT,
    created_at REAL,
    started_at REAL,
    finished_at REAL,
    pid INTEGER DEFAULT -1,
    return_value BLOB,
    error TEXT,
    log_path TEXT,
    user TEXT,
    schedule_type TEXT,
    server_id TEXT
);
CREATE TABLE IF NOT EXISTS server_heartbeats (
    server_id TEXT PRIMARY KEY,
    last_seen REAL
);
"""

# queue name -> max concurrent request processes (per server replica)
_CONCURRENCY = {'long': 4, 'short': 16}

# Multi-replica liveness: each server's worker loop heartbeats; the
# leader's stale sweep re-queues requests claimed by servers that
# stopped heartbeating (crashed replica -> another replica reruns the
# request; entrypoints are idempotent by construction — launches go
# through the failover provisioner, schedule_request dedups by id).
HEARTBEAT_INTERVAL = 5.0
DEFAULT_STALE_AFTER = 30.0

_SERVER_ID = os.environ.get('SKYPILOT_API_SERVER_ID')


def set_server_id(server_id: str) -> None:
    """Identity of this API-server replica (host:port by default,
    set at server startup). Scopes restart recovery to our own rows
    and lets peers attribute ours to us."""
    global _SERVER_ID
    if not os.environ.get('SKYPILOT_API_SERVER_ID'):
        _SERVER_ID = server_id


def get_server_id() -> str:
    if _SERVER_ID:
        return _SERVER_ID
    import socket
    return socket.gethostname()


class RequestStatus(enum.Enum):
    PENDING = 'PENDING'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


@functools.lru_cache(maxsize=None)
def _db_for(path: str) -> db_utils.SQLiteDB:
    db = db_utils.open_db(path, _CREATE_SQL)
    db.add_column_if_missing('requests', 'server_id', 'TEXT')
    return db


def _db() -> db_utils.SQLiteDB:
    return _db_for(os.path.join(constants.api_server_dir(), 'requests.db'))


def _log_path(request_id: str) -> str:
    d = os.path.join(constants.api_server_dir(), 'requests')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{request_id}.log')


# ---------------------------------------------------------------------------
# Submission
# ---------------------------------------------------------------------------
def schedule_request(name: str, entrypoint: str, payload: Dict[str, Any],
                     schedule_type: str = 'long',
                     user: str = 'unknown',
                     request_id: Optional[str] = None) -> str:
    """Persist a request; the scheduler thread picks it up.

    A client-supplied `request_id` makes scheduling idempotent: a
    retried POST (lost response over a flaky network) re-inserts
    nothing and returns the same id, so network-level retries can
    never double-run a launch.
    """
    request_id = request_id or uuid.uuid4().hex[:16]
    _db().execute(
        'INSERT OR IGNORE INTO requests (request_id, name, entrypoint, '
        'payload, status, created_at, log_path, user, schedule_type) '
        'VALUES (?,?,?,?,?,?,?,?,?)',
        (request_id, name, entrypoint, json.dumps(payload),
         RequestStatus.PENDING.value, time.time(), _log_path(request_id),
         user, schedule_type))
    return request_id


def get_request(request_id: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM requests WHERE request_id=?',
                          (request_id,))
    if row is None:
        return None
    out = dict(row)
    out['status'] = RequestStatus(out['status'])
    out['payload'] = json.loads(out['payload']) if out['payload'] else {}
    if out.get('return_value') is not None:
        out['return_value'] = pickle.loads(out['return_value'])
    if out.get('error'):
        out['error'] = json.loads(out['error'])
    return out


def list_requests(limit: int = 100) -> List[Dict[str, Any]]:
    rows = _db().query(
        'SELECT request_id, name, status, created_at, finished_at, user '
        'FROM requests ORDER BY created_at DESC LIMIT ?', (limit,))
    return rows


def cancel_request(request_id: str) -> bool:
    row = _db().query_one('SELECT pid, status, server_id FROM requests '
                          'WHERE request_id=?', (request_id,))
    if row is None:
        raise exceptions.RequestNotFoundError(request_id)
    status = RequestStatus(row['status'])
    if status.is_terminal():
        return False
    _set_status(request_id, RequestStatus.CANCELLED)
    # Kill only a process WE own: a replica-local pid belonging to a
    # peer server is someone else's process. The owning replica's
    # worker loop notices the CANCELLED status and kills its own tree.
    if row['pid'] and row['pid'] > 0 and \
            row.get('server_id') in (None, get_server_id()):
        subprocess_utils.kill_process_tree(row['pid'])
    return True


def requeue_stale_requests(stale_after: Optional[float] = None) -> int:
    """Re-queue RUNNING requests claimed by replicas that stopped
    heartbeating (crashed/partitioned server): back to PENDING so a
    live replica reruns them — at-least-once semantics; entrypoints
    are idempotent (launches ride the failover provisioner, and
    schedule_request dedups on request_id). Leader-only daemon job."""
    if stale_after is None:
        stale_after = float(os.environ.get('SKYPILOT_STALE_AFTER',
                                           DEFAULT_STALE_AFTER))
    now = time.time()
    # Heartbeat rows of long-dead replicas are useless after every
    # stale judgment that could involve them; without GC the table
    # grows one row per pod restart forever.
    _db().execute('DELETE FROM server_heartbeats WHERE last_seen < ?',
                  (now - max(10 * stale_after, 3600.0),))
    live = {r['server_id'] for r in _db().query(
        'SELECT server_id FROM server_heartbeats WHERE last_seen > ?',
        (now - stale_after,))}
    rows = _db().query(
        'SELECT request_id, server_id FROM requests WHERE status=? '
        'AND server_id IS NOT NULL', (RequestStatus.RUNNING.value,))
    n = 0
    for row in rows:
        if row['server_id'] in live:
            continue
        n += _db().execute_rowcount(
            'UPDATE requests SET status=?, server_id=NULL, pid=-1 '
            'WHERE request_id=? AND status=? AND server_id=?',
            (RequestStatus.PENDING.value, row['request_id'],
             RequestStatus.RUNNING.value, row['server_id']))
    return n


def gc_requests(retention_seconds: float) -> int:
    """Drop terminal requests that finished more than
    `retention_seconds` ago, along with their log files; returns how
    many rows were removed. Reference: sky/server/daemons.py's
    request-log maintenance; bounds requests.db + the log dir on a
    long-lived server."""
    cutoff = time.time() - retention_seconds
    terminal = tuple(s.value for s in RequestStatus if s.is_terminal())
    marks = ','.join('?' * len(terminal))
    rows = _db().query(
        f'SELECT request_id, log_path FROM requests '
        f'WHERE status IN ({marks}) AND finished_at IS NOT NULL '
        f'AND finished_at < ?', terminal + (cutoff,))
    for row in rows:
        if row.get('log_path'):
            try:
                os.unlink(row['log_path'])
            except OSError:
                pass
        _db().execute('DELETE FROM requests WHERE request_id=?',
                      (row['request_id'],))
    return len(rows)


def _set_status(request_id: str, status: RequestStatus,
                **extra: Any) -> None:
    sets = ['status=?']
    params: List[Any] = [status.value]
    for k, v in extra.items():
        sets.append(f'{k}=?')
        params.append(v)
    if status == RequestStatus.RUNNING:
        sets.append('started_at=?')
        params.append(time.time())
    if status.is_terminal():
        sets.append('finished_at=?')
        params.append(time.time())
    params.append(request_id)
    _db().execute(f'UPDATE requests SET {", ".join(sets)} '
                  'WHERE request_id=?', tuple(params))


# ---------------------------------------------------------------------------
# Execution (worker process)
# ---------------------------------------------------------------------------
def _resolve_entrypoint(entrypoint: str) -> Callable:
    module_name, fn_name = entrypoint.rsplit('.', 1)
    module = importlib.import_module(module_name)
    return getattr(module, fn_name)


def _request_worker_main(request_id: str, entrypoint: str,
                         payload_json: str, log_path: str,
                         db_path: str, user: str = 'unknown',
                         server_id: Optional[str] = None) -> None:
    """Runs in the forked worker process (reference:
    _request_execution_wrapper, executor.py:670)."""
    os.setpgrp()  # own process group: cancel kills the whole tree
    # The fork inherits aiohttp's asyncio signal handlers, which are
    # no-ops without the parent's event loop — a worker would silently
    # IGNORE SIGTERM (cancel, chaos kill). Restore default dispositions.
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_DFL)
    db = _db_for(db_path)
    import sys
    log_file = open(log_path, 'ab', buffering=0)
    os.dup2(log_file.fileno(), sys.stdout.fileno())
    os.dup2(log_file.fileno(), sys.stderr.fileno())
    from skypilot_tpu.utils import request_context
    request_context.set_request_user(user)
    # Terminal writes are guarded on (server_id, RUNNING) like every
    # other post-claim write: an ORPHANED worker (its server crashed,
    # the leader re-queued the row, a peer re-claimed it) must not
    # clobber the rerun's row — and a finished worker must not flip a
    # CANCELLED row back to a terminal result.
    guard = ' AND status=?' + (' AND server_id=?' if server_id else '')
    gparams: tuple = (RequestStatus.RUNNING.value,)
    if server_id:
        gparams += (server_id,)
    try:
        fn = _resolve_entrypoint(entrypoint)
        payload = json.loads(payload_json)
        result = fn(**payload)
        db.execute(
            f'UPDATE requests SET status=?, return_value=?, '
            f'finished_at=? WHERE request_id=?{guard}',
            (RequestStatus.SUCCEEDED.value, pickle.dumps(result),
             time.time(), request_id) + gparams)
    except BaseException as e:  # pylint: disable=broad-except
        traceback.print_exc()
        db.execute(
            f'UPDATE requests SET status=?, error=?, finished_at=? '
            f'WHERE request_id=?{guard}',
            (RequestStatus.FAILED.value,
             json.dumps(exceptions.serialize_exception(e)), time.time(),
             request_id) + gparams)


class RequestWorkerLoop:
    """Scheduler thread: spawns worker processes for pending requests."""

    def __init__(self) -> None:
        self._running: Dict[str, multiprocessing.Process] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_heartbeat = 0.0

    def _heartbeat(self) -> None:
        now = time.time()
        if now - self._last_heartbeat < HEARTBEAT_INTERVAL:
            return
        _db().execute(
            'INSERT OR REPLACE INTO server_heartbeats '
            '(server_id, last_seen) VALUES (?, ?)',
            (get_server_id(), now))
        self._last_heartbeat = now

    def start(self) -> None:
        # Recover orphaned requests from a previous SAME-HOST server
        # run (legacy NULL-server rows too): pids are host-scoped, so
        # a dead pid here proves the worker is gone — fail fast, the
        # single-server restart contract. Rows claimed on OTHER hosts
        # are left alone: their liveness is judged by heartbeat
        # (requeue_stale_requests), not by our local pid table.
        import socket
        host_prefix = f'{socket.gethostname()}:'
        for row in _db().query(
                'SELECT request_id, pid, status, server_id FROM requests '
                'WHERE status IN (?, ?)', (RequestStatus.RUNNING.value,
                                           RequestStatus.PENDING.value)):
            sid = row.get('server_id')
            if sid is not None and sid != get_server_id() and \
                    not sid.startswith(host_prefix):
                continue
            if sid is not None and sid != get_server_id() and \
                    not (row['pid'] and row['pid'] > 0):
                # A same-host PEER's row with no pid yet is MID-CLAIM
                # (pid lands after proc.start()): not provably dead —
                # leave it to the peer (or, if the peer is gone, to
                # the heartbeat stale sweep). Our OWN rows have no
                # such grace: nothing of ours runs at our startup.
                continue
            if RequestStatus(row['status']) == RequestStatus.RUNNING and \
                    not subprocess_utils.process_alive(row['pid']):
                _set_status(row['request_id'], RequestStatus.FAILED,
                            error=json.dumps({
                                'type': 'ApiRequestError',
                                'message': 'server restarted mid-request',
                            }))
        self._heartbeat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._step()
            except Exception:  # pylint: disable=broad-except
                traceback.print_exc()
            time.sleep(0.2)

    def _step(self) -> None:
        # Liveness first: a replica must be visibly alive BEFORE it
        # claims work, or the leader's stale sweep could re-queue a
        # request this replica just started.
        self._heartbeat()

        # Reap finished processes; kill local trees whose request was
        # CANCELLED on a peer replica (the peer cannot reach our pid).
        # ONE batched status query per poll, not one per worker.
        rows_by_id: Dict[str, Dict[str, Any]] = {}
        if self._running:
            marks = ','.join('?' * len(self._running))
            rows_by_id = {
                r['request_id']: r
                for r in _db().query(
                    f'SELECT request_id, status, server_id FROM requests '
                    f'WHERE request_id IN ({marks})',
                    tuple(self._running))}
        for rid, proc in list(self._running.items()):
            row = rows_by_id.get(rid)
            status = RequestStatus(row['status']) if row else None
            if proc.is_alive():
                if status == RequestStatus.CANCELLED:
                    subprocess_utils.kill_process_tree(proc.pid)
                continue
            proc.join()
            if status is not None and not status.is_terminal() and \
                    row.get('server_id') == get_server_id():
                # Worker died without recording a result. Guarded on
                # server_id: a stale-requeued row re-claimed by a peer
                # is the PEER's run now — not ours to fail.
                _db().execute(
                    'UPDATE requests SET status=?, error=?, finished_at=? '
                    'WHERE request_id=? AND server_id=? AND status=?',
                    (RequestStatus.FAILED.value, json.dumps({
                        'type': 'ApiRequestError',
                        'message': f'worker exited rc={proc.exitcode} '
                                   'without result',
                    }), time.time(), rid, get_server_id(),
                     row['status']))
            del self._running[rid]

        # Concurrency is per replica: count OUR running requests.
        counts: Dict[str, int] = {'long': 0, 'short': 0}
        rows = _db().query(
            'SELECT request_id, schedule_type FROM requests '
            'WHERE status=? AND server_id=?',
            (RequestStatus.RUNNING.value, get_server_id()))
        for r in rows:
            counts[r['schedule_type'] or 'long'] = counts.get(
                r['schedule_type'] or 'long', 0) + 1

        pending = _db().query(
            'SELECT * FROM requests WHERE status=? ORDER BY created_at',
            (RequestStatus.PENDING.value,))
        for req in pending:
            queue = req['schedule_type'] or 'long'
            if counts.get(queue, 0) >= _CONCURRENCY.get(queue, 4):
                continue
            if not self._claim(req['request_id']):
                continue  # a peer replica won the row
            self._spawn(req)
            counts[queue] = counts.get(queue, 0) + 1

    def _claim(self, request_id: str) -> bool:
        """Atomic multi-replica claim: exactly one server flips the
        row PENDING -> RUNNING (conditional UPDATE; the rowcount says
        who won)."""
        return _db().execute_rowcount(
            'UPDATE requests SET status=?, server_id=?, started_at=? '
            'WHERE request_id=? AND status=?',
            (RequestStatus.RUNNING.value, get_server_id(), time.time(),
             request_id, RequestStatus.PENDING.value)) == 1

    def _spawn(self, req: Dict[str, Any]) -> None:
        ctx = multiprocessing.get_context('fork')
        # daemon=True: workers die with the server (in-flight requests
        # are marked FAILED on restart by start()'s recovery scan);
        # workers only spawn subprocess.Popen children, which daemonic
        # processes are allowed to do.
        proc = ctx.Process(
            target=_request_worker_main,
            args=(req['request_id'], req['entrypoint'], req['payload'],
                  req['log_path'],
                  os.path.join(constants.api_server_dir(), 'requests.db'),
                  req['user'] or 'unknown', get_server_id()),
            daemon=True)
        # Both post-claim writes are guarded on (server_id, status):
        # if this replica stalled past the stale window and the leader
        # re-queued + a peer re-claimed the row, a late unguarded
        # UPDATE would clobber the peer's attribution (and a wrong pid
        # is a wrong kill target on the peer's host).
        guard = ('AND server_id=? AND status=?',
                 (get_server_id(), RequestStatus.RUNNING.value))
        try:
            proc.start()
        except Exception:
            # Spawn failed after the claim: give the row back.
            _db().execute(
                f'UPDATE requests SET status=?, server_id=NULL, pid=-1 '
                f'WHERE request_id=? {guard[0]}',
                (RequestStatus.PENDING.value, req['request_id']) +
                guard[1])
            raise
        _db().execute(
            f'UPDATE requests SET pid=? WHERE request_id=? {guard[0]}',
            (proc.pid, req['request_id']) + guard[1])
        self._running[req['request_id']] = proc
