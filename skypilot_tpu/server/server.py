"""API server: aiohttp app fronting the request executor.

Reference: sky/server/server.py (3607 LoC, FastAPI, 62 routes). Every
mutating endpoint schedules an async request and returns
`request_id`; `/api/get` resolves it, `/api/stream` tails its log
(the reference contract at sky/server/server.py:1771-1786).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import pickle
import time
from typing import Any, Dict, Optional

from aiohttp import web

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.agent import log_lib
from skypilot_tpu.observability import REGISTRY
from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.server import versions
from skypilot_tpu.server.requests import executor
from skypilot_tpu.utils import db_utils

API_VERSION = versions.API_VERSION

logger = logging.getLogger(__name__)

routes = web.RouteTableDef()


def _user(request: web.Request) -> str:
    """Server-derived identity set by auth_middleware."""
    return request.get('sky_user', 'unknown')


def _role(request: web.Request) -> str:
    return request.get('sky_role', 'admin')


from skypilot_tpu.server.route_utils import scheduled_handler as _mutating


# -- async request endpoints (reference: /launch, /exec, ...) ----------------
_API = 'skypilot_tpu.server.core_api'
_ENDPOINTS = {
    '/launch': ('launch', f'{_API}.launch', 'long'),
    '/exec': ('exec', f'{_API}.exec', 'long'),
    '/start': ('start', f'{_API}.start', 'long'),
    '/stop': ('stop', f'{_API}.stop', 'long'),
    '/down': ('down', f'{_API}.down', 'long'),
    '/autostop': ('autostop', f'{_API}.autostop', 'short'),
    '/status': ('status', f'{_API}.status', 'short'),
    '/queue': ('queue', f'{_API}.queue', 'short'),
    '/cancel': ('cancel', f'{_API}.cancel', 'short'),
    '/cost_report': ('cost_report', f'{_API}.cost_report', 'short'),
    '/storage/ls': ('storage_ls', f'{_API}.storage_ls', 'short'),
    '/storage/delete': ('storage_delete', f'{_API}.storage_delete', 'long'),
    '/check': ('check', f'{_API}.check', 'short'),
    '/accelerators': ('list_accelerators', f'{_API}.list_accelerators',
                      'short'),
    # managed jobs + serve are registered by their own modules below
}


# -- request lifecycle --------------------------------------------------------
async def api_get(request: web.Request) -> web.Response:
    request_id = request.query.get('request_id', '')
    timeout = float(request.query.get('timeout', 0) or 0)
    deadline = asyncio.get_event_loop().time() + timeout if timeout else None
    while True:
        record = executor.get_request(request_id)
        if record is None:
            return web.json_response({'error': 'request not found'},
                                     status=404)
        if record['status'].is_terminal():
            break
        if deadline and asyncio.get_event_loop().time() > deadline:
            break
        await asyncio.sleep(0.3)
    body: Dict[str, Any] = {
        'request_id': request_id,
        'name': record['name'],
        'status': record['status'].value,
        # Which replica ran/owns it (multi-server deployments).
        'server_id': record.get('server_id'),
    }
    if record['status'] == executor.RequestStatus.SUCCEEDED:
        # Pickle-over-JSON for rich return values (handles are not
        # shipped to clients; core_api returns plain data).
        body['return_value'] = record['return_value']
    elif record['status'] == executor.RequestStatus.FAILED:
        body['error'] = record['error']
    return web.json_response(body)


async def api_stream(request: web.Request) -> web.StreamResponse:
    from skypilot_tpu.server.route_utils import stream_lines
    request_id = request.query.get('request_id', '')
    follow = request.query.get('follow', '1') == '1'
    record = executor.get_request(request_id)
    if record is None:
        return web.json_response({'error': 'request not found'}, status=404)

    # Multi-replica: request logs are REPLICA-LOCAL files. A request
    # that ran on a peer streams from that peer (server_id is
    # host:port, directly dialable inside the deployment) — clients
    # can hit any replica behind one Service and still get logs.
    owner = record.get('server_id')
    if owner and owner != executor.get_server_id() and \
            not os.path.exists(record['log_path']) and \
            request.query.get('noproxy') != '1':
        return await _proxy_peer_stream(request, owner, request_id,
                                        follow)

    def finished() -> bool:
        rec = executor.get_request(request_id)
        return rec is None or rec['status'].is_terminal()

    return await stream_lines(
        request,
        lambda: log_lib.tail_logs(record['log_path'], follow=follow,
                                  stop_condition=finished))


async def _proxy_peer_stream(request: web.Request, owner: str,
                             request_id: str,
                             follow: bool) -> web.StreamResponse:
    """Relay /api/stream from the replica that ran the request.
    `noproxy=1` on the hop prevents a loop if the peer's log file is
    also gone (it then serves its own empty answer)."""
    import aiohttp
    url = (f'http://{owner}/api/stream?request_id={request_id}'
           f'&follow={"1" if follow else "0"}&noproxy=1')
    headers = {}
    auth = request.headers.get('Authorization')
    if auth:
        headers['Authorization'] = auth
    try:
        timeout = aiohttp.ClientTimeout(total=None, sock_connect=5)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            async with session.get(url, headers=headers) as upstream:
                resp = web.StreamResponse(
                    status=upstream.status,
                    headers={'Content-Type':
                             upstream.headers.get('Content-Type',
                                                  'text/plain')})
                await resp.prepare(request)
                async for chunk in upstream.content.iter_chunked(8192):
                    await resp.write(chunk)
                await resp.write_eof()
                return resp
    except Exception as e:  # pylint: disable=broad-except
        return web.json_response(
            {'error': f'request ran on replica {owner}, which is not '
                      f'reachable from here: {e}'}, status=502)


async def api_cancel(request: web.Request) -> web.Response:
    from skypilot_tpu.users import permission
    body = await request.json()
    request_id = body.get('request_id', '')
    record = executor.get_request(request_id)
    try:
        permission.check_request_cancel(record, _user(request),
                                        _role(request))
    except permission.PermissionDeniedError as e:
        return web.json_response({'error': str(e)}, status=403)
    try:
        cancelled = executor.cancel_request(request_id)
    except exceptions.RequestNotFoundError:
        return web.json_response({'error': 'request not found'}, status=404)
    return web.json_response({'cancelled': cancelled})


async def api_status(request: web.Request) -> web.Response:
    limit = int(request.query.get('limit', 100))
    return web.json_response({'requests': executor.list_requests(limit)})


async def api_health(request: web.Request) -> web.Response:
    return web.json_response({
        'status': 'healthy',
        'api_version': API_VERSION,
        'commit': os.environ.get('SKYPILOT_COMMIT', 'dev'),
    })


_SERVER_START_TIME = None  # set in run()


def _refresh_orchestration_gauges() -> None:
    """Populate the registry's orchestration gauges (clusters, managed
    jobs, services, request records) from the DB aggregates. Pure
    aggregate queries (no handle unpickling), run off the event loop;
    a broken table loses only its own section — loudly, via the
    logger and the skypilot_scrape_errors_total counter (the old
    traceback.print_exc-to-stdout was invisible to log shippers)."""
    errors = obs_catalog.counter('skypilot_scrape_errors_total')

    def section(name, fn) -> None:
        try:
            fn()
        except Exception:  # pylint: disable=broad-except
            errors.labels(section=name).inc()
            logger.exception('metrics scrape: %s section failed '
                             '(losing the section, not the scrape)',
                             name)

    def clusters():
        from skypilot_tpu import global_state
        gauge = obs_catalog.gauge('skypilot_clusters')
        gauge.clear()  # a status that emptied must not linger
        for status, count in sorted(
                global_state.cluster_status_counts().items()):
            gauge.labels(status=status).set(count)

    def jobs():
        from skypilot_tpu.jobs import state as jobs_state
        gauge = obs_catalog.gauge('skypilot_managed_jobs')
        gauge.clear()
        for status, count in sorted(jobs_state.status_counts().items()):
            gauge.labels(status=status).set(count)

    def serve():
        from skypilot_tpu.serve import serve_state
        obs_catalog.gauge('skypilot_services').set(
            serve_state.count_services())
        obs_catalog.gauge('skypilot_service_replicas_ready').set(
            serve_state.count_ready_replicas())

    def requests_by_status():
        counts: Dict[str, int] = {}
        for row in executor.list_requests(limit=10000):
            counts[row['status']] = counts.get(row['status'], 0) + 1
        # Running totals recomputed from the source of truth each
        # scrape (exposed under TYPE counter: catalog gauge_as_counter).
        gauge = obs_catalog.gauge('skypilot_requests_total')
        gauge.clear()
        for status, count in sorted(counts.items()):
            gauge.labels(status=status.lower()).set(count)

    for name, fn in (('clusters', clusters), ('jobs', jobs),
                     ('serve', serve),
                     ('requests', requests_by_status)):
        section(name, fn)


def _refresh_process_gauges() -> None:
    import psutil
    proc = psutil.Process()
    obs_catalog.gauge('skypilot_server_rss_bytes').set(
        proc.memory_info().rss)
    children_rss = 0
    for child in proc.children(recursive=True):
        try:
            children_rss += child.memory_info().rss
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            pass  # worker exited between snapshot and read
    obs_catalog.gauge('skypilot_workers_rss_bytes').set(children_rss)
    if _SERVER_START_TIME is not None:
        obs_catalog.gauge('skypilot_server_uptime_seconds').set(
            round(time.time() - _SERVER_START_TIME))


async def api_metrics(request: web.Request) -> web.Response:
    """Prometheus text exposition of the process registry (reference:
    sky/server/metrics.py): orchestration gauges + per-route request
    counters/latency histograms (metrics_middleware) + process RSS."""
    del request
    await asyncio.get_event_loop().run_in_executor(
        None, _refresh_orchestration_gauges)
    _refresh_process_gauges()
    return web.Response(text=REGISTRY.render(),
                        content_type='text/plain')


async def cluster_job_logs(request: web.Request) -> web.StreamResponse:
    """Proxy job logs from a cluster's head agent (keeps clients thin)."""
    from skypilot_tpu import global_state
    cluster = request.query.get('cluster', '')
    job_id = request.query.get('job_id')
    follow = request.query.get('follow', '1') == '1'
    tail = int(request.query.get('tail', 0))
    rank_q = request.query.get('rank')
    rank = None
    if rank_q not in (None, ''):
        if not rank_q.isdigit():
            return web.json_response(
                {'error': f'rank must be a non-negative integer, '
                          f'got {rank_q!r}'}, status=400)
        rank = int(rank_q)
    record = global_state.get_cluster(cluster)
    if record is None:
        return web.json_response({'error': f'no cluster {cluster}'},
                                 status=404)
    handle = record['handle']
    agent = handle.agent()
    if job_id is None:
        jobs = agent.get_jobs(limit=1)
        if not jobs:
            return web.json_response({'error': 'no jobs'}, status=404)
        job_id = jobs[0]['job_id']

    def lines():
        try:
            yield from agent.stream_job_logs(int(job_id), follow=follow,
                                             tail=tail, rank=rank)
        except Exception as e:  # pylint: disable=broad-except
            yield f'[server] log stream error: {e}\n'

    from skypilot_tpu.server.route_utils import stream_lines
    return await stream_lines(request, lines)


@web.middleware
async def metrics_middleware(request: web.Request, handler):
    """Per-route request count / latency / in-flight — outermost, so
    auth rejections and 404s are counted too. The route label is the
    matched route template (bounded cardinality), never the raw
    path."""
    in_flight = obs_catalog.gauge('skypilot_api_requests_in_flight')
    start = time.perf_counter()
    in_flight.inc()
    code = 500  # an escaped non-HTTP exception is a server error
    try:
        response = await handler(request)
        code = response.status
        return response
    except web.HTTPException as e:
        code = e.status
        raise
    finally:
        in_flight.dec()
        try:
            resource = request.match_info.route.resource
        except Exception:  # pylint: disable=broad-except  # stpu: ignore[SKY005] — fallback label 'unmatched' IS the handling
            resource = None
        route = (resource.canonical if resource is not None
                 else 'unmatched')
        obs_catalog.counter('skypilot_api_requests_total').labels(
            route=route, method=request.method, code=str(code)).inc()
        obs_catalog.histogram('skypilot_api_request_seconds').labels(
            route=route, method=request.method).observe(
                time.perf_counter() - start)


def create_app() -> web.Application:
    app = web.Application(middlewares=[metrics_middleware,
                                       auth_middleware])
    for path, (name, entrypoint, schedule_type) in _ENDPOINTS.items():
        app.router.add_post(path, _mutating(name, entrypoint, schedule_type))
    app.router.add_get('/api/get', api_get)
    app.router.add_get('/api/stream', api_stream)
    app.router.add_post('/api/cancel', api_cancel)
    app.router.add_get('/api/status', api_status)
    app.router.add_get('/api/health', api_health)
    app.router.add_get('/api/metrics', api_metrics)
    app.router.add_get('/logs', cluster_job_logs)
    # Managed jobs + serve route groups:
    try:
        from skypilot_tpu.jobs import server as jobs_server
        jobs_server.register(app)
    except ImportError:
        pass
    try:
        from skypilot_tpu.serve import server as serve_server
        serve_server.register(app)
    except ImportError:
        pass
    try:
        from skypilot_tpu.batch import server as batch_server
        batch_server.register(app)
    except ImportError:
        pass
    from skypilot_tpu.server import dashboard
    dashboard.register(app)
    from skypilot_tpu.server import attach as attach_mod
    attach_mod.register(app)

    # Server plugins (reference: sky/server/plugin_hooks.py): modules
    # named in `api_server.plugins` may register extra routes/hooks.
    from skypilot_tpu import sky_config
    import importlib as _importlib
    for plugin_path in sky_config.get_nested(('api_server',
                                              'plugins')) or []:
        try:
            module = _importlib.import_module(str(plugin_path))
            register_fn = getattr(module, 'register', None)
            if register_fn is None:
                raise AttributeError(
                    f'plugin {plugin_path} has no register(app)')
            register_fn(app)
            print(f'Loaded server plugin {plugin_path}.')
        except Exception as e:  # pylint: disable=broad-except
            # A broken plugin must not take the whole server down.
            print(f'Failed to load server plugin {plugin_path!r}: {e!r}')

    from skypilot_tpu.users import core as users_core
    from skypilot_tpu.users import tokens as tokens_lib

    def _admin_only(request: web.Request) -> Optional[web.Response]:
        if _role(request) != 'admin':
            return web.json_response(
                {'error': f'admin role required (you are '
                          f'{_user(request)!r}, role {_role(request)!r})'},
                status=403)
        return None

    async def users_ls(request: web.Request) -> web.Response:
        del request
        loop = asyncio.get_event_loop()
        return web.json_response(
            {'users': await loop.run_in_executor(None, users_core.ls)})

    async def users_set_role(request: web.Request) -> web.Response:
        denied = _admin_only(request)
        if denied:
            return denied
        body = await request.json()
        user = body.get('user')
        if not user:
            return web.json_response({'error': 'missing user'}, status=400)
        try:
            await asyncio.get_event_loop().run_in_executor(
                None, users_core.set_role, user, body.get('role', 'user'))
        except KeyError as e:
            return web.json_response({'error': str(e)}, status=404)
        except ValueError as e:
            return web.json_response({'error': str(e)}, status=400)
        return web.json_response({'ok': True})

    async def tokens_issue(request: web.Request) -> web.Response:
        denied = _admin_only(request)
        if denied:
            return denied
        body = await request.json()
        try:
            out = await asyncio.get_event_loop().run_in_executor(
                None, tokens_lib.issue, body['user'],
                body.get('role', 'user'))
        except (KeyError, ValueError) as e:
            return web.json_response({'error': str(e)}, status=400)
        return web.json_response(out)

    async def tokens_ls(request: web.Request) -> web.Response:
        denied = _admin_only(request)
        if denied:
            return denied
        loop = asyncio.get_event_loop()
        return web.json_response(
            {'tokens': await loop.run_in_executor(None, tokens_lib.ls)})

    async def tokens_revoke(request: web.Request) -> web.Response:
        denied = _admin_only(request)
        if denied:
            return denied
        body = await request.json()
        ok = await asyncio.get_event_loop().run_in_executor(
            None, tokens_lib.revoke, body.get('token_id', ''))
        return web.json_response({'revoked': ok})

    app.router.add_get('/users', users_ls)
    app.router.add_post('/users/role', users_set_role)
    app.router.add_post('/users/tokens', tokens_issue)
    app.router.add_get('/users/tokens', tokens_ls)
    app.router.add_post('/users/tokens/revoke', tokens_revoke)
    return app


@web.middleware
async def auth_middleware(request: web.Request, handler):
    """Identity + auth (reference: sky/server/auth/, sky/users/).

    Three postures, decided per request:
      - per-user service tokens exist → every request (except
        /api/health) must present one; identity/role come from the
        token, *not* the spoofable X-Skypilot-User header;
      - only a static bootstrap token is configured
        (SKYPILOT_API_TOKEN / api_server.auth_token) → it must be
        presented; the bearer is treated as admin and identity falls
        back to the header;
      - neither → open local mode: header identity, admin role.

    All sqlite work runs off the event loop (ADVICE r1: the per-request
    user upsert was a synchronous write inside async middleware).
    """
    import os as _os
    from skypilot_tpu import sky_config
    from skypilot_tpu.users import core as users_core
    from skypilot_tpu.users import tokens as tokens_lib

    # Version negotiation (reference: sky/server/versions.py): reject
    # clients below the minimum compatible version with an actionable
    # message; absent header = legacy v1, still in range.
    _negotiated, version_err = versions.check_compatibility(
        request.headers.get(versions.HEADER), remote_side='client')
    if version_err:
        return web.json_response({'error': version_err}, status=400,
                                 headers={versions.HEADER:
                                          str(versions.API_VERSION)})

    loop = asyncio.get_event_loop()
    supplied = request.headers.get('Authorization', '')
    bearer = supplied[7:] if supplied.startswith('Bearer ') else ''
    static_token = (_os.environ.get('SKYPILOT_API_TOKEN') or
                    sky_config.get_nested(('api_server', 'auth_token')))

    user = request.headers.get('X-Skypilot-User') or 'unknown'
    role = 'admin'
    # Open paths: liveness probe + the dashboard's static shell (no
    # data; the SPA's own /dashboard/api calls DO require the token,
    # which the page prompts for).
    open_paths = ('/api/health', '/dashboard', '/dashboard/app.js')
    if request.path not in open_paths:
        from skypilot_tpu.users import oidc
        tokens_on = await loop.run_in_executor(None,
                                               tokens_lib.auth_required)
        oidc_on = oidc.enabled()
        if oidc_on and bearer and oidc.looks_like_jwt(bearer):
            # OIDC bearer JWTs: identity from verified claims
            # (reference: sky/server/auth/ OAuth middleware).
            ident = await loop.run_in_executor(None, oidc.verify_jwt,
                                               bearer)
            if ident is None:
                return web.json_response({'error': 'unauthorized'},
                                         status=401)
            user, role = ident['user'], ident['role']
        elif tokens_on:
            if static_token and bearer == static_token:
                pass  # bootstrap admin keeps header identity
            else:
                ident = await loop.run_in_executor(
                    None, tokens_lib.authenticate, bearer)
                if ident is None:
                    return web.json_response({'error': 'unauthorized'},
                                             status=401)
                user, role = ident['user'], ident['role']
        elif static_token:
            if bearer != static_token:
                return web.json_response({'error': 'unauthorized'},
                                         status=401)
        elif oidc_on:
            # OIDC configured and nothing else matched: JWT required.
            return web.json_response({'error': 'unauthorized'},
                                     status=401)
    request['sky_user'] = user
    request['sky_role'] = role
    if user and user != 'unknown':
        try:
            await loop.run_in_executor(None, users_core.record_request, user)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug('user registry update failed (best-effort): %s',
                         e)
    response = await handler(request)
    try:
        response.headers[versions.HEADER] = str(versions.API_VERSION)
    except Exception:  # pylint: disable=broad-except  # stpu: ignore[SKY005] — streamed responses may already have headers sent
        pass
    return response


def run(host: str = '127.0.0.1',
        port: int = constants.API_SERVER_PORT) -> None:
    global _SERVER_START_TIME
    import time as _time
    _SERVER_START_TIME = _time.time()
    # Replica identity: scopes restart recovery to our own request
    # rows, keys the heartbeat peers judge our liveness by, AND is a
    # dialable host:port (cross-replica log streaming connects to it).
    # SKYPILOT_API_SERVER_HOST overrides the host part (k8s: the pod
    # IP — pod names don't resolve under a non-headless Service);
    # SKYPILOT_API_SERVER_ID overrides the whole identity. The
    # identity host is NOT the bind host: the server must still bind
    # the caller-supplied address (loopback by default — on hosts
    # whose hostname resolves off-loopback, binding the identity would
    # silently expose an intended-local server, or refuse local
    # clients).
    import socket as _socket
    id_host = os.environ.get('SKYPILOT_API_SERVER_HOST') or \
        _socket.gethostname()
    executor.set_server_id(f'{id_host}:{port}')
    worker_loop = executor.RequestWorkerLoop()
    worker_loop.start()
    # HA: re-adopt managed jobs orphaned by a previous server/controller
    # crash (reference: sky/jobs/managed_job_refresh_thread.py), and
    # respawn dead serve controllers on their recorded ports.
    try:
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        jobs_scheduler.maybe_schedule_next_jobs()
    except Exception:  # pylint: disable=broad-except
        import traceback
        traceback.print_exc()
    try:
        from skypilot_tpu.serve import core as serve_core
        serve_core.reconcile_controllers()
    except Exception:  # pylint: disable=broad-except
        import traceback
        traceback.print_exc()
    # Periodic maintenance (reference: sky/server/daemons.py): status
    # reconcile + controller liveness + request GC keep the DB honest
    # even when nobody polls. Each interval is env-tunable and <= 0
    # disables THAT job only.
    from skypilot_tpu.server import daemons as daemons_lib
    daemons = daemons_lib.ServerDaemons(
        status_interval=float(os.environ.get(
            'SKYPILOT_STATUS_REFRESH_INTERVAL',
            daemons_lib.DEFAULT_STATUS_INTERVAL)),
        liveness_interval=float(os.environ.get(
            'SKYPILOT_LIVENESS_SWEEP_INTERVAL',
            daemons_lib.DEFAULT_LIVENESS_INTERVAL)),
        gc_interval=float(os.environ.get(
            'SKYPILOT_REQUEST_GC_INTERVAL',
            daemons_lib.DEFAULT_GC_INTERVAL)),
        request_retention=float(os.environ.get(
            'SKYPILOT_REQUEST_RETENTION',
            daemons_lib.DEFAULT_REQUEST_RETENTION)),
        stale_requeue_interval=float(os.environ.get(
            'SKYPILOT_STALE_REQUEUE_INTERVAL',
            daemons_lib.DEFAULT_STALE_REQUEUE_INTERVAL)),
        # Leader-only across replicas: pg advisory lock when the state
        # layer is Postgres, flock on the single-host sqlite default.
        leader_lock=db_utils.AdvisoryLock(
            'server-daemons', constants.api_server_dir()))
    daemons.start()
    app = create_app()
    web.run_app(app, host=host, port=port, print=None)


if __name__ == '__main__':
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int,
                        default=constants.API_SERVER_PORT)
    args = parser.parse_args()
    run(args.host, args.port)
