"""Managed-job controller: one process per job; launches, monitors,
recovers.

Reference: sky/jobs/controller.py (JobController :152) — builds the
task, launches the user cluster via execution.launch
(_is_launched_by_jobs_controller=True), monitors job status via the
cluster's agent, and on preemption drives the recovery strategy. The
checkpoint contract is the reference's (SURVEY §2.6): the task mounts
a bucket (MOUNT/MOUNT_CACHED); recovery re-launches the cluster and
re-mounts it; the app resumes from its checkpoint.
"""
from __future__ import annotations

import argparse
import signal
import sys
import time
import traceback
from typing import Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_state
from skypilot_tpu import task as task_lib
from skypilot_tpu.agent import job_lib as agent_job_lib
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state
from skypilot_tpu.robustness import faults
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import ux_utils

import os

_POLL_SECONDS = float(os.environ.get('SKYPILOT_JOBS_POLL_SECONDS', '5'))
_UNREACHABLE_GRACE_SECONDS = float(
    os.environ.get('SKYPILOT_JOBS_UNREACHABLE_GRACE_SECONDS', '30'))


class JobCancelled(Exception):
    pass


class JobController:

    def __init__(self, job_id: int, adopt: bool = False) -> None:
        self.job_id = job_id
        record = state.get_job(job_id)
        assert record is not None, job_id
        self.record = record
        self.adopt = adopt
        self.base_cluster_name = record['cluster_name']
        self.pooled = bool(record.get('pool'))
        self.group = record.get('job_group')
        # Pipelines (reference: `sky jobs launch pipeline.yaml`): a
        # list task_config runs stages sequentially, one cluster each.
        cfg = record['task_config']
        self.stage_configs = cfg if isinstance(cfg, list) else [cfg]
        self._enter_stage(int(record.get('stage') or 0))
        self._cancelled = False
        signal.signal(signal.SIGTERM, self._handle_term)

    def _enter_stage(self, stage: int) -> None:
        self.stage = stage
        cfg = self.stage_configs[stage]
        self.cluster_name = (
            self.base_cluster_name if len(self.stage_configs) == 1
            else f'{self.base_cluster_name}-s{stage}')
        if self.pooled:
            self.cluster_name = self.base_cluster_name  # pool worker
        self.task = task_lib.Task.from_yaml_config(cfg)
        self.executor = recovery_strategy.StrategyExecutor.make(
            self.cluster_name, self.task)
        if self.group:
            # Set at construction (not only in _launch_group_member):
            # an adopted controller that goes straight into recovery
            # must still install the peer-hostname block pre-submit.
            self.executor.pre_exec_hook = self._group_pre_exec
        # Per-stage restart budget: each stage's own
        # job_recovery.max_restarts_on_errors governs it (a pipeline's
        # later stages must not inherit stage 0's setting or pay for
        # restarts an earlier stage consumed). The job-record value
        # only applies to single-task jobs.
        self.stage_max_restarts = (
            self.record['max_restarts_on_errors']
            if len(self.stage_configs) == 1 else 0)
        for r in self.task.resources:
            if r.job_recovery:
                self.stage_max_restarts = int(
                    r.job_recovery.get('max_restarts_on_errors', 0))
                break
        self._stage_restarts = 0

    def _handle_term(self, signum, frame):  # noqa: ARG002
        self._cancelled = True

    # ------------------------------------------------------------------
    def run(self) -> state.ManagedJobStatus:
        job_id = self.job_id
        try:
            self._reap_stale_stage_clusters(self.stage)
            if self.adopt:
                agent_job_id = self._adopt()
                final = self._monitor_loop(agent_job_id)
                if final == state.ManagedJobStatus.SUCCEEDED:
                    final = self._run_stages(self.stage + 1)
            else:
                final = self._run_stages(self.stage)
        except JobCancelled:
            self._cleanup(cancel_job=True)
            state.set_status(job_id, state.ManagedJobStatus.CANCELLED)
            return state.ManagedJobStatus.CANCELLED
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(job_id, state.ManagedJobStatus.FAILED_NO_RESOURCE,
                             last_error=str(e))
            return state.ManagedJobStatus.FAILED_NO_RESOURCE
        except Exception as e:  # pylint: disable=broad-except
            traceback.print_exc()
            self._cleanup(cancel_job=False)
            state.set_status(job_id, state.ManagedJobStatus.FAILED_CONTROLLER,
                             last_error=common_utils.format_exception(e))
            return state.ManagedJobStatus.FAILED_CONTROLLER
        state.set_status(job_id, final)
        return final

    def _run_stages(self, start_stage: int) -> state.ManagedJobStatus:
        """Execute stages sequentially from `start_stage`; each stage
        gets its own cluster, recovery budget, and cleanup."""
        for stage in range(start_stage, len(self.stage_configs)):
            if stage != self.stage:
                self._enter_stage(stage)
            state.set_stage(self.job_id, stage)
            if len(self.stage_configs) > 1:
                ux_utils.log(
                    f'Managed job {self.job_id}: stage '
                    f'{stage + 1}/{len(self.stage_configs)} '
                    f'({self.task.name or "unnamed"}).')
            state.set_status(self.job_id, state.ManagedJobStatus.STARTING)
            agent_job_id = self._launch(first=True)
            final = self._monitor_loop(agent_job_id)
            if final != state.ManagedJobStatus.SUCCEEDED:
                return final
        return state.ManagedJobStatus.SUCCEEDED

    def _reap_stale_stage_clusters(self, current_stage: int) -> None:
        """The stage pointer advances BEFORE the finished stage's
        cluster teardown (crash-safety for side effects); if the
        controller died inside that window, the finished stage's
        cluster is still up — tear it down here on resume."""
        if len(self.stage_configs) == 1 or self.pooled:
            return
        from skypilot_tpu import core as sky_core
        for k in range(current_stage):
            stale = f'{self.base_cluster_name}-s{k}'
            if global_state.get_cluster(stale) is None:
                continue
            ux_utils.log(f'Managed job {self.job_id}: reaping stale '
                         f'stage-{k} cluster {stale}.')
            try:
                sky_core.down(stale)
            except Exception as e:  # pylint: disable=broad-except
                ux_utils.error(f'Failed to reap {stale}: {e}')

    # ------------------------------------------------------------------
    def _adopt(self) -> int:
        """Resume watching a job whose previous controller died.

        HA contract (reference: sky/jobs/managed_job_refresh_thread.py):
        the DB carries the controller intent (cluster + agent job id);
        if the cluster and on-cluster job are still alive we simply
        re-enter the monitor loop — the user job never notices. If the
        job was mid-cancel, finish the cancel. Otherwise fall through
        to recovery (relaunch), which the checkpoint contract makes
        safe.
        """
        job_id = self.job_id
        record = state.get_job(job_id)
        assert record is not None
        agent_job_id = record.get('agent_job_id') or -1
        if record['status'] == state.ManagedJobStatus.CANCELLING:
            ux_utils.log(f'Adopted job {job_id} mid-cancel; finishing.')
            raise JobCancelled()
        ux_utils.log(f'Adopting managed job {job_id} '
                     f'(cluster {self.cluster_name}, '
                     f'agent job {agent_job_id}).')
        agent = self._agent()
        if agent is not None and agent_job_id > 0:
            try:
                job = agent.get_job(agent_job_id)
            except Exception as e:  # pylint: disable=broad-except
                ux_utils.log(f'Managed job {job_id}: adoption probe of '
                             f'agent job {agent_job_id} failed ({e}); '
                             f'treating the cluster as lost.')
                job = None
            if job is not None:
                # Only *consecutive* failed adoptions count: a clean
                # re-attach resets the give-up counter.
                state.reset_adopt_attempts(job_id)
                return agent_job_id  # cluster + job alive: just watch
        # Cluster or job gone while unwatched → normal recovery path.
        agent_job_id = self._recover()
        state.reset_adopt_attempts(job_id)
        return agent_job_id

    def _launch(self, first: bool) -> int:
        """(Re)launch cluster + submit the job; returns agent job id.

        The strategy executor's launch performs the full stage walk
        (for an existing cluster it skips provision but re-syncs and
        re-mounts checkpoint buckets) and submits the job once.
        For job-group members the launch is two-phase: provision first,
        publish this cluster's head address, wait for every peer, then
        submit with the peer addresses injected
        (reference: sky/jobs/job_group_networking.py:1-21).
        """
        del first
        if self.group:
            agent_job_id = self._launch_group_member()
        else:
            agent_job_id = self.executor.launch()
        state.set_agent_job_id(self.job_id, agent_job_id)
        return agent_job_id

    def _launch_group_member(self) -> int:
        from skypilot_tpu.jobs import groups
        # Phase 1: provision + setup only (run=None boot task).
        boot = task_lib.Task.from_yaml_config(self.record['task_config'])
        boot.run = None
        execution.launch(boot, cluster_name=self.cluster_name,
                         detach_run=True, _quiet_optimizer=True,
                         _is_launched_by_jobs_controller=True)
        record = global_state.get_cluster(self.cluster_name)
        assert record is not None
        head = record['handle'].cluster_info.get_head_instance()
        groups.publish_address(self.job_id, head.internal_ip)
        # Phase 2: exchange addresses, then submit the real job. The
        # hostname block is installed via the pre-exec hook — between
        # (re)provision and job submission — so jobs that resolve
        # peers at startup never race it, on launch OR recovery.
        groups.wait_peer_addresses(self.group, self.job_id)
        self.executor.task = self.task
        self.executor.pre_exec_hook = self._group_pre_exec
        return self.executor.launch()

    def _group_pre_exec(self, handle) -> None:
        """Pre-submission cluster prep for a group member: publish the
        (possibly new) head address, refresh the peer-address env vars
        from the DB (an ADOPTED controller's task was rebuilt from the
        stored config and has none), and install the peer hostname
        block. Hostname injection failures DEGRADE (warn) rather than
        fail the member — the peer-address env vars remain the source
        of truth, and failing here would abort the whole group."""
        from skypilot_tpu.jobs import groups
        head = handle.cluster_info.get_head_instance()
        if head is not None:
            groups.publish_address(self.job_id, head.internal_ip)
        self.task.update_envs({
            'SKYPILOT_JOBGROUP': self.group,
            'SKYPILOT_JOBGROUP_HOSTS_FILE':
                groups.hosts_file_path(self.group),
            **groups.peer_addresses(self.group),
        })
        try:
            groups.install_hosts_entries(handle, self.group)
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(
                f'Job group {self.group!r}: hostname injection failed '
                f'({e}); continuing with env addresses only.')

    def _agent(self):
        record = global_state.get_cluster(self.cluster_name)
        if record is None:
            return None
        return record['handle'].agent()

    def _zone(self) -> Optional[str]:
        """Zone the job's cluster is (or was last) placed in — the
        scope key storm fault plans match on, and the label on
        skypilot_jobs_preemptions_total."""
        record = global_state.get_cluster(self.cluster_name)
        if record is None:
            return None
        launched = getattr(record['handle'], 'launched_resources',
                           None)
        return getattr(launched, 'zone', None)

    def _monitor_loop(self, agent_job_id: int) -> state.ManagedJobStatus:
        job_id = self.job_id
        unreachable_since: Optional[float] = None
        state.set_status(job_id, state.ManagedJobStatus.RUNNING)
        while True:
            if self._cancelled:
                raise JobCancelled()
            time.sleep(_POLL_SECONDS)
            # External failure sources (health monitors, maintenance
            # schedulers) short-circuit the probe/grace machinery:
            # a reported failure recovers NOW.
            from skypilot_tpu.jobs import failure_sources
            ext_reason = failure_sources.check_failed(self.cluster_name)
            if ext_reason is not None:
                ux_utils.log(
                    f'Managed job {job_id}: external failure source '
                    f'reports cluster {self.cluster_name} failed '
                    f'({ext_reason}); recovering.')
                agent_job_id = self._recover()
                unreachable_since = None
                continue
            agent = self._agent()
            status: Optional[agent_job_lib.JobStatus] = None
            if agent is not None:
                try:
                    # Chaos: a DROP (or injected RequestException)
                    # here is a synthetic preemption — the probe
                    # counts as unreachable, and after the grace
                    # window the normal recovery path runs. The
                    # zone/job context lets SCOPED rules (e.g. a
                    # jobs.preempt_storm rule with scope
                    # {"zone": ...}) take down exactly the jobs a
                    # real zone-wide spot storm would.
                    if faults.point('jobs.monitor_probe',
                                    zone=self._zone() or '',
                                    job=str(job_id)) is \
                            faults.DROP:
                        raise requests.RequestException(
                            'injected monitor-probe drop')
                    job = agent.get_job(agent_job_id)
                    status = job['status'] if job else None
                    unreachable_since = None
                except requests.RequestException:
                    pass
            if agent is None or (status is None and
                                 unreachable_since is None):
                unreachable_since = unreachable_since or time.time()
            if unreachable_since is not None:
                if time.time() - unreachable_since < \
                        _UNREACHABLE_GRACE_SECONDS and agent is not None:
                    continue
                # Preemption / cluster loss → recover.
                agent_job_id = self._recover()
                unreachable_since = None
                continue

            if status is None or not status.is_terminal():
                continue
            if status.is_recoverable():
                # Typed trainer exits (train_guard.py): a graceful
                # preemption-notice checkpoint (rc 83) or a watchdog
                # abort of a hung step (rc 84). Both take the
                # PREEMPTING -> RECOVERING relaunch path and do NOT
                # consume the user-failure restart budget — the
                # checkpoint contract makes the relaunch resume
                # where the trainer stopped.
                from skypilot_tpu.observability import (catalog as
                                                        obs_catalog)
                preempted = (status ==
                             agent_job_lib.JobStatus.PREEMPTED)
                if preempted:
                    obs_catalog.counter(
                        'skypilot_train_preempt_notices_total').inc()
                else:
                    obs_catalog.counter(
                        'skypilot_train_watchdog_aborts_total').inc()
                ux_utils.log(
                    f'Managed job {job_id}: trainer exited '
                    f'{status.value} (typed recoverable exit); '
                    f'recovering.')
                state.set_status(job_id,
                                 state.ManagedJobStatus.PREEMPTING)
                agent_job_id = self._recover(preemption=preempted)
                unreachable_since = None
                continue
            if status == agent_job_lib.JobStatus.SUCCEEDED:
                # Pipelines: persist the advance BEFORE cleanup — a
                # controller crash in between must make the adopted
                # controller resume at the NEXT stage, never re-run a
                # succeeded stage's side effects.
                if self.stage + 1 < len(self.stage_configs):
                    state.set_stage(job_id, self.stage + 1)
                    state.set_agent_job_id(job_id, -1)
                self._cleanup(cancel_job=False)
                return state.ManagedJobStatus.SUCCEEDED
            if status == agent_job_lib.JobStatus.CANCELLED:
                return state.ManagedJobStatus.CANCELLED
            # User-code failure: restart if this STAGE's budget remains
            # (recovery_count stays the job-wide visible total).
            state.bump_recovery(job_id)
            self._stage_restarts += 1
            max_restarts = self.stage_max_restarts
            if self._stage_restarts <= max_restarts:
                ux_utils.log(
                    f'Managed job {job_id}: user failure; restart '
                    f'{self._stage_restarts}/{max_restarts}.')
                agent_job_id = self._launch(first=False)
                state.set_status(job_id, state.ManagedJobStatus.RUNNING)
                continue
            self._cleanup(cancel_job=False)
            return (state.ManagedJobStatus.FAILED_SETUP
                    if status == agent_job_lib.JobStatus.FAILED_SETUP
                    else state.ManagedJobStatus.FAILED)

    def _recover(self, preemption: bool = True) -> int:
        """Relaunch + resubmit. `preemption=False` (watchdog aborts)
        skips the zone-preemption counter — a hang is not a spot
        reclaim — but still records the recovery event the latency
        accounting is computed from."""
        job_id = self.job_id
        zone = self._zone()
        state.set_status(job_id, state.ManagedJobStatus.RECOVERING)
        state.bump_recovery(job_id)
        # Fleet-level preemption signals: the zone-labeled counter
        # (a spiking label = a zone melting down) and the per-event
        # timestamps recovery latency is computed from.
        if preemption:
            from skypilot_tpu.observability import (catalog as
                                                    obs_catalog)
            obs_catalog.counter(
                'skypilot_jobs_preemptions_total').labels(
                    zone=zone or 'unknown').inc()
        state.record_preemption(job_id, zone)
        ux_utils.log(f'Managed job {job_id}: cluster lost; recovering.')
        agent_job_id = self.executor.recover()
        state.set_agent_job_id(job_id, agent_job_id)
        state.record_recovered(job_id)
        if self.group:
            # Own publish + own-cluster hosts install already happened
            # pre-submit (the executor's _group_pre_exec hook). Here:
            # refresh the hosts block on every PEER cluster so their
            # stable hostnames point at this member's new head.
            from skypilot_tpu.jobs import groups
            for member in groups.members(self.group):
                if member['job_id'] == job_id:
                    continue
                peer_cluster = member.get('cluster_name')
                peer_record = (global_state.get_cluster(peer_cluster)
                               if peer_cluster else None)
                if peer_record is None:
                    continue
                try:
                    groups.install_hosts_entries(
                        peer_record['handle'], self.group)
                except Exception as e:  # pylint: disable=broad-except
                    ux_utils.log(
                        f'Job group {self.group!r}: hosts refresh on '
                        f'{peer_cluster!r} failed: {e}')
        state.set_status(job_id, state.ManagedJobStatus.RUNNING)
        return agent_job_id

    def _cleanup(self, cancel_job: bool) -> None:
        if cancel_job:
            agent = self._agent()
            if agent is not None:
                try:
                    jobs = agent.get_jobs()
                    for j in jobs:
                        if not j['status'].is_terminal():
                            agent.cancel_job(j['job_id'])
                except requests.RequestException:
                    pass
        if self.group and self.pooled:
            # Strip the group's hostname block before the worker is
            # RELEASED for reuse: a later job on it must not resolve
            # 'actor'/'learner' to IPs the cloud may have reassigned
            # to strangers. (Non-pooled clusters are terminated, so
            # there is nothing to strip — and stripping would race
            # still-running peers on shared-host setups like the
            # Local cloud.)
            record = global_state.get_cluster(self.cluster_name)
            if record is not None:
                from skypilot_tpu.jobs import groups
                groups.remove_hosts_entries(record['handle'], self.group)
        if self.pooled:
            # Pool workers are released, not destroyed — the whole point
            # of the pool is cluster reuse across jobs.
            ux_utils.log(f'Releasing pool worker {self.cluster_name}.')
            return
        self.executor.terminate_cluster()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    parser.add_argument('--adopt', action='store_true',
                        help='re-attach to a job whose controller died')
    args = parser.parse_args()
    controller = JobController(args.job_id, adopt=args.adopt)
    final = controller.run()
    # Wake the scheduler for the next pending job.
    from skypilot_tpu.jobs import scheduler
    scheduler.maybe_schedule_next_jobs()
    sys.exit(0 if final == state.ManagedJobStatus.SUCCEEDED else 1)


if __name__ == '__main__':
    main()
