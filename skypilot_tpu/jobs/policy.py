"""Checkpoint-cadence + spot-economics policy for managed jobs.

The quantitative glue between the catalog's `PreemptionRate` column
and everything that consumes it (the optimizer's effective-cost
scoring, the fleet bench, user-facing cadence advice). The model is
the classic Young/Daly first-order analysis of checkpointed
computation under memoryless interrupts:

  - A zone preempts spot capacity at rate lambda (preemptions /
    hour; the catalog column). Interrupts are modeled as Poisson.
  - Writing a checkpoint costs `ckpt_overhead_s` seconds of paused
    progress; after a preemption the job pays `relaunch_s` seconds
    of relaunch/provision time plus, in expectation, HALF a
    checkpoint interval of lost progress.
  - The Young optimum balances checkpoint tax against expected
    loss: tau* = sqrt(2 * ckpt_overhead / lambda).

`spot_overhead_fraction` is then the fraction of paid machine time
that produces no retained progress:

    ckpt_overhead/tau  +  lambda * (tau/2 + relaunch)

and `effective_cost_multiplier` = 1 + that fraction: multiply a spot
price by it and two zones become comparable on *delivered* work, not
list price. That is the `price x E[restarts]`-style score the
optimizer ranks spot placements by.

All rates are per HOUR (matching the catalog); all durations are
SECONDS (matching every other knob in this codebase).
"""
from __future__ import annotations

import math
from typing import Optional

#: Defaults for the overhead model when the caller has no better
#: numbers: a large-model checkpoint write to a bucket (~1 min) and
#: a TPU-slice relaunch + restore (~5 min).
DEFAULT_CKPT_OVERHEAD_S = 60.0
DEFAULT_RELAUNCH_S = 300.0

#: Cadence clamp: never advise checkpointing more often than once a
#: minute (write amplification) or less than once per day.
MIN_INTERVAL_S = 60.0
MAX_INTERVAL_S = 86400.0


def optimal_checkpoint_interval(
        preemption_rate_per_hour: float,
        ckpt_overhead_s: float = DEFAULT_CKPT_OVERHEAD_S) -> float:
    """Young's optimum tau* = sqrt(2 * delta / lambda), seconds.

    A zone losing capacity 0.5x/hour with 60s checkpoint writes
    wants a checkpoint roughly every 15.5 minutes; a stable reserved
    zone (rate ~0) wants the cadence ceiling.
    """
    if preemption_rate_per_hour <= 0.0:
        return MAX_INTERVAL_S
    rate_per_s = preemption_rate_per_hour / 3600.0
    tau = math.sqrt(2.0 * max(ckpt_overhead_s, 0.0) / rate_per_s)
    return min(max(tau, MIN_INTERVAL_S), MAX_INTERVAL_S)


def spot_overhead_fraction(
        preemption_rate_per_hour: float,
        ckpt_overhead_s: float = DEFAULT_CKPT_OVERHEAD_S,
        relaunch_s: float = DEFAULT_RELAUNCH_S,
        interval_s: Optional[float] = None) -> float:
    """Fraction of paid time lost to checkpoint tax + recovery.

    `interval_s` pins an actual checkpoint cadence; by default the
    job is assumed to run at the Young optimum for the zone's rate
    (the best case — a worse cadence only strengthens the ordering
    this feeds).
    """
    if preemption_rate_per_hour <= 0.0:
        return 0.0
    tau = (interval_s if interval_s is not None else
           optimal_checkpoint_interval(preemption_rate_per_hour,
                                       ckpt_overhead_s))
    tau = max(tau, 1.0)
    rate_per_s = preemption_rate_per_hour / 3600.0
    return (max(ckpt_overhead_s, 0.0) / tau +
            rate_per_s * (tau / 2.0 + max(relaunch_s, 0.0)))


def effective_cost_multiplier(
        preemption_rate_per_hour: float,
        ckpt_overhead_s: float = DEFAULT_CKPT_OVERHEAD_S,
        relaunch_s: float = DEFAULT_RELAUNCH_S,
        interval_s: Optional[float] = None) -> float:
    """price -> risk-adjusted price: 1 + spot_overhead_fraction.

    Monotone in the preemption rate, 1.0 at rate 0 — so ranking spot
    candidates by `price * multiplier` degrades gracefully to plain
    price ranking where the catalog carries no rate data.
    """
    return 1.0 + spot_overhead_fraction(
        preemption_rate_per_hour, ckpt_overhead_s, relaunch_s,
        interval_s)


def expected_restarts(preemption_rate_per_hour: float,
                      runtime_hours: float) -> float:
    """E[restarts] for a job of the given duration (Poisson mean)."""
    return max(preemption_rate_per_hour, 0.0) * max(runtime_hours, 0.0)
