"""Worker pools for managed jobs.

Reference: `sky jobs pool apply` (sky/jobs/ + shared pool code in
sky/serve/replica_managers.py:610) — pre-provisioned clusters that
managed jobs borrow instead of cold-launching: a pooled job skips
provisioning latency entirely, and the cluster is released back (not
torn down) when the job finishes.

Pool workers are ordinary clusters named `pool-<name>-w<i>`.
Assignment bookkeeping lives in the managed-jobs DB so the scheduler
can hand free workers to pending pooled jobs.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import ux_utils

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS job_pools (
    name TEXT PRIMARY KEY,
    task_config TEXT,
    num_workers INTEGER,
    created_at REAL
);
"""


_schema_ready: set = set()


def _db():
    db = state._db()  # pylint: disable=protected-access
    if id(db) not in _schema_ready:  # one-time per process
        with db.conn() as conn:
            conn.executescript(_CREATE_SQL)
        db.add_column_if_missing('job_pools', 'user', 'TEXT')
        _schema_ready.add(id(db))
    return db


def worker_cluster(pool: str, idx: int) -> str:
    return f'pool-{pool}-w{idx}'


def apply(pool_name: str, task_config: Dict[str, Any],
          num_workers: int) -> Dict[str, Any]:
    """Create/resize a pool: provision its worker clusters now.

    Shrinking tears down the surplus workers (idx >= new size) —
    refusing if any of them is busy — so no cluster keeps billing
    outside the pool record.
    """
    db = _db()
    # Validate the template (resources only; run/setup optional).
    template = task_lib.Task.from_yaml_config(dict(task_config))
    del template

    prev = get(pool_name)
    if prev is not None and prev['num_workers'] > num_workers:
        surplus = [worker_cluster(pool_name, idx)
                   for idx in range(num_workers, prev['num_workers'])]
        busy = set(_busy_workers(pool_name)) & set(surplus)
        if busy:
            raise exceptions.SkyError(
                f'Cannot shrink pool {pool_name!r}: {sorted(busy)} still '
                'run jobs; cancel them first.')
        from skypilot_tpu import core as sky_core
        for cluster in surplus:
            try:
                sky_core.down(cluster)
                ux_utils.log(f'Pool {pool_name}: released {cluster}.')
            except exceptions.ClusterDoesNotExist:
                pass

    from skypilot_tpu.utils import request_context
    db.execute(
        'INSERT INTO job_pools (name, task_config, num_workers, created_at, '
        'user) VALUES (?,?,?,?,?) ON CONFLICT(name) DO UPDATE SET '
        'task_config=excluded.task_config, '
        'num_workers=excluded.num_workers',
        (pool_name, json.dumps(task_config), num_workers, time.time(),
         request_context.get_request_user() or 'unknown'))
    provisioned = []
    for idx in range(num_workers):
        cluster = worker_cluster(pool_name, idx)
        boot = task_lib.Task.from_yaml_config(dict(task_config))
        boot.run = None  # provision + setup only
        _, handle = execution.launch(boot, cluster_name=cluster,
                                     detach_run=True, _quiet_optimizer=True)
        assert handle is not None
        provisioned.append(cluster)
        ux_utils.log(f'Pool {pool_name}: worker {cluster} ready.')
    return {'name': pool_name, 'workers': provisioned}


def get(pool_name: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM job_pools WHERE name=?',
                          (pool_name,))
    if row is None:
        return None
    out = dict(row)
    out['task_config'] = json.loads(out['task_config'] or '{}')
    return out


def ls() -> List[Dict[str, Any]]:
    out = []
    for row in _db().query('SELECT * FROM job_pools ORDER BY name'):
        pool = dict(row)
        pool['task_config'] = json.loads(pool['task_config'] or '{}')
        pool['busy_workers'] = len(_busy_workers(pool['name']))
        out.append(pool)
    return out


def status(pool_name: str) -> List[Dict[str, Any]]:
    """Per-worker rows: cluster name, cluster status, running job."""
    pool = get(pool_name)
    if pool is None:
        raise exceptions.SkyError(f'Pool {pool_name!r} not found.')
    from skypilot_tpu import global_state
    job_by_worker = dict(_active_jobs_by_worker(pool_name))
    out = []
    for idx in range(pool['num_workers']):
        cname = worker_cluster(pool_name, idx)
        record = global_state.get_cluster(cname)
        cluster_status = record['status'] if record else 'NOT_FOUND'
        out.append({
            'worker': cname,
            # Enum -> str: rows cross the HTTP boundary as JSON.
            'status': getattr(cluster_status, 'value', cluster_status),
            'job_id': job_by_worker.get(cname),
        })
    return out


def down(pool_name: str) -> None:
    pool = get(pool_name)
    if pool is None:
        raise exceptions.SkyError(f'Pool {pool_name!r} not found.')
    busy = _busy_workers(pool_name)
    if busy:
        raise exceptions.SkyError(
            f'Pool {pool_name!r} has active jobs on {sorted(busy)}; '
            'cancel them first.')
    from skypilot_tpu import core as sky_core
    for idx in range(pool['num_workers']):
        try:
            sky_core.down(worker_cluster(pool_name, idx))
        except exceptions.ClusterDoesNotExist:
            pass
    _db().execute('DELETE FROM job_pools WHERE name=?', (pool_name,))


# ---------------------------------------------------------------------------
# Assignment (called under the scheduler lock)
# ---------------------------------------------------------------------------
def _active_jobs_by_worker(pool_name: str) -> List[tuple]:
    """(worker, job_id) for every non-terminal job in the pool —
    the single definition of 'busy' (terminal set from state._TERMINAL
    so new terminal statuses can't drift out of sync here)."""
    terminal = sorted(st.value for st in state._TERMINAL)  # pylint: disable=protected-access
    placeholders = ','.join('?' * len(terminal))
    rows = _db().query(
        f'SELECT pool_worker, job_id FROM managed_jobs WHERE pool=? '
        f'AND status NOT IN ({placeholders}) '
        f'AND pool_worker IS NOT NULL',
        (pool_name, *terminal))
    return [(r['pool_worker'], r['job_id']) for r in rows]


def _busy_workers(pool_name: str) -> List[str]:
    return [w for w, _ in _active_jobs_by_worker(pool_name)]


def assign_worker(pool_name: str) -> Optional[str]:
    """A free worker cluster name, or None if the pool is saturated."""
    pool = get(pool_name)
    if pool is None:
        raise exceptions.SkyError(f'Pool {pool_name!r} not found.')
    busy = set(_busy_workers(pool_name))
    for idx in range(pool['num_workers']):
        cluster = worker_cluster(pool_name, idx)
        if cluster not in busy:
            return cluster
    return None
