"""Managed-jobs verbs (server-side entrypoints).

Reference: sky/jobs/server/core.py — launch/queue/cancel/logs.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state


def launch(task_config, name: Optional[str] = None,
           user: Optional[str] = None,
           pool: Optional[str] = None) -> Dict[str, Any]:
    """Submit a managed job; returns its id immediately. With `pool`,
    the job borrows a pre-provisioned pool worker instead of
    cold-launching a cluster. A LIST of task configs is a pipeline
    (reference: `sky jobs launch pipeline.yaml`): stages run
    sequentially, one cluster each, with per-stage recovery."""
    if pool is not None:
        from skypilot_tpu.jobs import pools as pools_lib
        if pools_lib.get(pool) is None:
            raise exceptions.SkyError(
                f'Pool {pool!r} not found; `stpu jobs pool apply` first.')
        if isinstance(task_config, list) and len(task_config) > 1:
            raise exceptions.SkyError(
                'Pipelines and pools do not combine: each stage needs '
                'its own cluster lifecycle.')
    # Validate every stage config early (fail fast in the request).
    from skypilot_tpu import task as task_lib
    stages = (task_config if isinstance(task_config, list)
              else [task_config])
    if not stages:
        raise exceptions.SkyError('Pipeline needs at least one task.')
    tasks = [task_lib.Task.from_yaml_config(dict(cfg)) for cfg in stages]
    task = tasks[0]
    max_restarts = 0
    strategy = 'default'
    for r in task.resources:
        if r.job_recovery:
            max_restarts = int(r.job_recovery.get('max_restarts_on_errors',
                                                  0))
            strategy = r.job_recovery.get('strategy') or strategy
    # Identity: prefer the server-derived request user over any
    # payload-supplied name (the payload is client-controlled).
    from skypilot_tpu.utils import request_context
    user = request_context.get_request_user() or user or 'unknown'
    job_id = state.submit_job(name or task.name, task_config, strategy,
                              max_restarts, user, pool=pool)
    scheduler.maybe_schedule_next_jobs()
    return {'job_id': job_id, 'controller': 'local', 'pool': pool}


def queue(refresh: bool = False,
          skip_finished: bool = False) -> List[Dict[str, Any]]:
    if refresh:
        scheduler.maybe_schedule_next_jobs()
    jobs = state.get_jobs()
    if skip_finished:
        jobs = [j for j in jobs if not j['status'].is_terminal()]
    out = []
    for j in jobs:
        out.append({
            'job_id': j['job_id'],
            'name': j['name'],
            'status': j['status'].value,
            'cluster_name': j['cluster_name'],
            'submitted_at': j['submitted_at'],
            'started_at': j['started_at'],
            'ended_at': j['ended_at'],
            'recovery_count': j['recovery_count'],
            'strategy': j['strategy'],
            'last_error': j['last_error'],
            'user': j['user'],
            'pool': j.get('pool'),
            'pool_worker': j.get('pool_worker'),
            'stage': (f"{int(j.get('stage') or 0) + 1}"
                      f"/{len(j['task_config'])}"
                      if isinstance(j['task_config'], list) else None),
        })
    return out


def pool_apply(task_config: Dict[str, Any], pool_name: str,
               num_workers: int = 1) -> Dict[str, Any]:
    from skypilot_tpu.jobs import pools as pools_lib
    return pools_lib.apply(pool_name, task_config, num_workers)


def pool_ls() -> List[Dict[str, Any]]:
    from skypilot_tpu.jobs import pools as pools_lib
    return pools_lib.ls()


def pool_down(pool_name: str) -> None:
    from skypilot_tpu.jobs import pools as pools_lib
    pools_lib.down(pool_name)


def pool_status(pool_name: str) -> List[Dict[str, Any]]:
    from skypilot_tpu.jobs import pools as pools_lib
    return pools_lib.status(pool_name)


def cancel(job_ids: Optional[List[int]] = None,  # noqa: D401
           all_jobs: bool = False) -> List[int]:
    """Cancel jobs by id (RBAC: users/permission.py gates non-owners
    at the HTTP boundary under the payload key `job_ids`/`all_jobs`)."""
    if all_jobs:
        job_ids = [j['job_id'] for j in state.get_jobs()
                   if not j['status'].is_terminal()]
    cancelled = []
    for job_id in job_ids or []:
        if scheduler.cancel_job(int(job_id)):
            cancelled.append(int(job_id))
    return cancelled


def get_log_path(job_id: int) -> str:
    job = state.get_job(job_id)
    if job is None:
        raise exceptions.JobNotFoundError(f'managed job {job_id}')
    return job['log_path']


def is_terminal(job_id: int) -> bool:
    job = state.get_job(job_id)
    return job is None or job['status'].is_terminal()


# -- job groups (reference: sky/jobs/job_group_networking.py) ---------------
def group_launch(group_name: str, task_configs: List[Dict[str, Any]],
                 user: Optional[str] = None,
                 strategy: Optional[str] = None,
                 max_restarts_on_errors: int = 0) -> Dict[str, Any]:
    from skypilot_tpu.jobs import groups
    from skypilot_tpu.utils import request_context
    user = request_context.get_request_user() or user or 'unknown'
    job_ids = groups.launch_group(group_name, task_configs, user,
                                  strategy, max_restarts_on_errors)
    return {'group': group_name, 'job_ids': job_ids}


def group_status(group_name: str) -> List[Dict[str, Any]]:
    from skypilot_tpu.jobs import groups
    out = []
    for j in groups.members(group_name):
        out.append({
            'job_id': j['job_id'],
            'name': j['name'],
            'status': j['status'].value,
            'cluster_name': j['cluster_name'],
            'head_ip': j.get('head_ip'),
            'recovery_count': j['recovery_count'],
            'last_error': j['last_error'],
        })
    return out


def group_cancel(group_name: str) -> List[int]:
    from skypilot_tpu.jobs import groups
    return groups.cancel_group(group_name)
