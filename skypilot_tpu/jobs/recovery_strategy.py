"""Recovery strategies: how a managed job's cluster is (re)launched.

Reference: sky/jobs/recovery_strategy.py (1107 LoC) —
`JOBS_RECOVERY_STRATEGY_REGISTRY` with FAILOVER (:896) and
EAGER_NEXT_REGION (:1017); `StrategyExecutor` (:81) wraps
launch/recover with retries.

TPU-specific: preemptions cluster by zone-capacity, so
EAGER_NEXT_REGION (jump to a different region immediately on
preemption) is the default for spot TPU slices, FAILOVER (retry the
same zone first — best for reserved capacity) otherwise.
"""
from __future__ import annotations

import os
import time
import typing
from typing import Any, Dict, Optional, Set

from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.robustness import faults
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.registry import JOBS_RECOVERY_STRATEGY_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.backends import tpu_backend

_MAX_LAUNCH_ATTEMPTS = 3
_RETRY_GAP_SECONDS = 5
# Overall retry-deadline default: per-attempt backoff alone lets a
# permanently failing launch spin forever (10 attempts with a 60s
# backoff cap is minutes, but recover() is itself retried by the
# monitor loop). One hour of failed (re)launching means the request
# is not going to be satisfied — surface FAILED instead.
_DEFAULT_LAUNCH_DEADLINE_SECONDS = float(
    os.environ.get('SKYPILOT_JOBS_LAUNCH_DEADLINE_SECONDS', '3600'))


class StrategyExecutor:
    """Launch/recover a managed job's cluster under a strategy."""

    # Registry name; also the `strategy` label on
    # skypilot_jobs_recovery_attempts_total.
    NAME = 'base'

    def __init__(self, cluster_name: str, task: 'task_lib.Task') -> None:
        self.cluster_name = cluster_name
        self.task = task
        self.blocked_resources: Set[Any] = set()
        # Job-group members set this (controller): runs on the cluster
        # handle between provision/setup and job submission, so peer
        # hostname injection precedes the user job even on recovery.
        self.pre_exec_hook = None
        # Herd knobs. `jitter` exists for A/B benching only (the
        # fleet bench proves the no-jitter herd is worse); `rng`
        # makes the jittered schedule reproducible (fleet sim seeds
        # one per job). `launch_deadline_s` caps TOTAL elapsed
        # (re)launch time across all attempts of one launch/recover
        # call — overridable per job via
        # `job_recovery.launch_deadline_seconds`.
        self.jitter = True
        self.rng: Optional[Any] = None
        self.launch_deadline_s = _DEFAULT_LAUNCH_DEADLINE_SECONDS
        for r in task.resources:
            if r.job_recovery and \
                    r.job_recovery.get('launch_deadline_seconds') \
                    is not None:
                self.launch_deadline_s = float(
                    r.job_recovery['launch_deadline_seconds'])
                break

    @classmethod
    def make(cls, cluster_name: str,
             task: 'task_lib.Task') -> 'StrategyExecutor':
        strategy = None
        for r in task.resources:
            if r.job_recovery:
                strategy = r.job_recovery.get('strategy')
                break
        if strategy is None:
            any_spot_tpu = any(r.use_spot and r.is_tpu_slice
                               for r in task.resources)
            strategy = ('eager_next_region' if any_spot_tpu else 'failover')
        strategy_cls = JOBS_RECOVERY_STRATEGY_REGISTRY.from_str(strategy)
        return strategy_cls(cluster_name, task)

    # -- operations -----------------------------------------------------------
    def launch(self) -> int:
        """Initial launch + job submission: returns the agent job id."""
        return self._launch_with_retries(first_launch=True)

    def recover(self) -> int:
        """Relaunch after a preemption/failure; returns new agent job
        id (strategy-specific)."""
        raise NotImplementedError

    def _checkpoint_preflight(self) -> Optional[Dict[str, Any]]:
        """Controller-side dry run of the job's restore fallback:
        when `job_recovery.checkpoint_dir` names a LOCAL checkpoint
        directory, verify its sha256 manifests before relaunching so
        the operator learns up front which step the relaunched job
        will actually resume from (the recipe's CheckpointManager
        falls back past corrupt steps on its own — this is the
        early-warning surface, not a gate; remote gs://-s3:// dirs
        are left to the object store's checksums). Never raises."""
        ckpt_dir = None
        for r in self.task.resources:
            if r.job_recovery and r.job_recovery.get('checkpoint_dir'):
                ckpt_dir = str(r.job_recovery['checkpoint_dir'])
                break
        if not ckpt_dir or ckpt_dir.startswith(('gs://', 's3://')):
            return None
        from skypilot_tpu.parallel import ckpt_integrity
        report = ckpt_integrity.preflight(os.path.expanduser(ckpt_dir))
        if report['corrupt_steps']:
            ux_utils.error(
                f'{self.cluster_name}: checkpoint step(s) '
                f'{report["corrupt_steps"]} in {ckpt_dir} failed '
                f'integrity verification; the relaunched job will '
                f'fall back to step {report["newest_verifying"]}.')
        elif report['steps']:
            ux_utils.log(
                f'{self.cluster_name}: checkpoint preflight clean — '
                f'resuming from step {report["newest_verifying"]}.')
        return report

    def terminate_cluster(self) -> None:
        from skypilot_tpu import core
        try:
            core.down(self.cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.error(f'Failed to clean up {self.cluster_name}: {e}')

    # -- helpers ---------------------------------------------------------------
    def _launch_with_retries(self, first_launch: bool,
                             max_attempts: int = _MAX_LAUNCH_ATTEMPTS
                             ) -> int:
        # Decorrelated jitter: after a zone-wide preemption, every
        # affected controller relaunches at once — jitter-free
        # exponential backoff keeps them colliding in lockstep.
        backoff = common_utils.Backoff(_RETRY_GAP_SECONDS,
                                       jitter=self.jitter,
                                       rng=self.rng)
        inflight = obs_catalog.gauge('skypilot_jobs_relaunch_inflight')
        start = time.monotonic()
        last_exc: Optional[Exception] = None
        for attempt in range(max_attempts):
            try:
                faults.point('jobs.launch', cluster=self.cluster_name)
                inflight.inc()
                try:
                    job_id, handle = execution.launch(
                        self.task,
                        cluster_name=self.cluster_name,
                        detach_run=True,
                        _quiet_optimizer=True,
                        _is_launched_by_jobs_controller=True,
                        _blocked_resources=self.blocked_resources or
                        None,
                        _pre_exec_hook=self.pre_exec_hook)
                finally:
                    inflight.dec()
                assert handle is not None and job_id is not None
                return job_id
            except (exceptions.ResourcesUnavailableError,
                    exceptions.ClusterSetUpError) as e:
                last_exc = e
                if first_launch and isinstance(
                        e, exceptions.ResourcesUnavailableError) and \
                        e.no_failover:
                    raise
                if isinstance(e, exceptions.ResourcesUnavailableError) \
                        and e.blocked_cloud:
                    # Account-level failure on that cloud: exclude it
                    # so the next attempt's optimizer picks elsewhere
                    # (or proves nothing else can serve the request).
                    from skypilot_tpu import resources as resources_mod
                    blocked = resources_mod.Resources(
                        cloud=e.blocked_cloud)
                    if any(b.cloud is not None and
                           b.cloud.canonical_name() == e.blocked_cloud and
                           b.region is None and b.zone is None
                           for b in self.blocked_resources):
                        # Already blocked and it failed again: every
                        # other option is exhausted too — give up
                        # instead of burning the remaining attempts.
                        raise
                    self.blocked_resources.add(blocked)
                ux_utils.log(
                    f'Launch attempt {attempt + 1}/{max_attempts} for '
                    f'{self.cluster_name} failed: '
                    f'{common_utils.format_exception(e)}')
                gap = backoff.current_backoff()
                # Overall retry deadline: a permanently failing
                # launch must surface as FAILED, not retry forever
                # (the per-attempt backoff bounds nothing by itself).
                if time.monotonic() - start + gap > \
                        self.launch_deadline_s:
                    raise exceptions.ResourcesUnavailableError(
                        f'Launch retry deadline '
                        f'({self.launch_deadline_s:.0f}s) exceeded '
                        f'for {self.cluster_name} after '
                        f'{attempt + 1} attempts; giving up.') from e
                time.sleep(gap)
        raise exceptions.ResourcesUnavailableError(
            f'Failed to launch cluster {self.cluster_name} after '
            f'{max_attempts} attempts.',
        ) if last_exc is None else last_exc


def _count_recovery_attempt(strategy: str) -> None:
    """Tick skypilot_jobs_recovery_attempts_total{strategy} — the
    fleet-level preemption-churn signal (a spiking rate on one
    strategy label means a zone is melting)."""
    obs_catalog.counter(
        'skypilot_jobs_recovery_attempts_total').labels(
            strategy=strategy).inc()


@JOBS_RECOVERY_STRATEGY_REGISTRY.register(name='failover', default=True)
class FailoverStrategyExecutor(StrategyExecutor):
    """Retry the same location first, then fail over elsewhere.

    Reference: recovery_strategy.py:896.
    """

    NAME = 'failover'

    def recover(self) -> int:
        _count_recovery_attempt(self.NAME)
        self._checkpoint_preflight()
        self.terminate_cluster()
        # Same resources, same preference order: the retrying
        # provisioner already walks zones/regions in order.
        return self._launch_with_retries(first_launch=False,
                                         max_attempts=10)


@JOBS_RECOVERY_STRATEGY_REGISTRY.register(name='eager_next_region')
class EagerNextRegionStrategyExecutor(StrategyExecutor):
    """Skip the preempted region immediately (spot TPU default).

    Reference: recovery_strategy.py:1017 — on preemption the same
    region's capacity is likely still tight; block it and move on.
    """

    NAME = 'eager_next_region'

    def recover(self) -> int:
        _count_recovery_attempt(self.NAME)
        self._checkpoint_preflight()
        from skypilot_tpu import global_state
        record = global_state.get_cluster(self.cluster_name)
        if record is not None:
            handle = record['handle']
            launched = handle.launched_resources
            if launched is not None and launched.region is not None:
                self.blocked_resources.add(
                    launched.copy(zone=None))
        self.terminate_cluster()
        # Prefer a different region; if nothing else has capacity (or
        # the cloud has a single region), fall back to the full set.
        try:
            return self._launch_with_retries(first_launch=False,
                                             max_attempts=3)
        except exceptions.ResourcesUnavailableError:
            if not self.blocked_resources:
                raise
            self.blocked_resources.clear()
            return self._launch_with_retries(first_launch=False,
                                             max_attempts=10)
