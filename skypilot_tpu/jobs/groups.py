"""Job groups: co-scheduled managed jobs that can reach each other.

Reference: sky/jobs/job_group_networking.py:1-21 + the job-group
co-optimization hook (sky/optimizer.py:1796) — N tasks submitted as
one unit (RL actor/learner pairs, disaggregated serving), scheduled
all-or-nothing, each task's env carrying every peer's head address.

Mechanics here: members share a `job_group` tag in the managed-jobs
DB. The scheduler admits the whole group or none. Each member's
controller provisions its cluster, publishes its head's internal IP
to the DB, waits for all peers to publish, then injects

    SKYPILOT_JOBGROUP=<group>
    SKYPILOT_JOBGROUP_ADDR_<TASKNAME>=<ip>   (one per member)

into the task env and submits the user job. On recovery the new
address is re-published; peers observe it by re-resolving at
reconnect time.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import state

_PUBLISH_TIMEOUT_SECONDS = 900.0


def _db():
    # job_group/head_ip columns are migrated once in state._db().
    return state._db()  # pylint: disable=protected-access


def launch_group(group_name: str, task_configs: List[Dict[str, Any]],
                 user: str, strategy: Optional[str] = None,
                 max_restarts_on_errors: int = 0) -> List[int]:
    """Atomically submit one managed job per task config.

    Every task needs a unique `name` (it keys the peer-address env
    var). Returns the managed-job ids, all PENDING until the scheduler
    can admit the entire group.
    """
    if not task_configs:
        raise exceptions.SkyError('Job group needs at least one task.')
    names = [cfg.get('name') for cfg in task_configs]
    if None in names or len(set(names)) != len(names):
        raise exceptions.SkyError(
            'Every task in a job group needs a unique name; got '
            f'{names}.')
    from skypilot_tpu.jobs import scheduler
    if len(task_configs) > scheduler.MAX_STARTING_JOBS:
        raise exceptions.SkyError(
            f'Job group {group_name!r} has {len(task_configs)} tasks; '
            f'all-or-nothing admission caps groups at '
            f'{scheduler.MAX_STARTING_JOBS} (the concurrent-start limit).')
    if _db().query_one(
            'SELECT job_id FROM managed_jobs WHERE job_group=? AND status '
            f'NOT IN ({",".join("?" * len(state._TERMINAL))})',  # pylint: disable=protected-access
            (group_name, *(s.value for s in state._TERMINAL))):  # pylint: disable=protected-access
        raise exceptions.SkyError(
            f'Job group {group_name!r} already has active jobs.')
    # Insert + tag under the scheduler lock: a concurrent scheduler pass
    # must never observe a member as a plain group-less PENDING job (it
    # would spawn it solo, skipping peer-address injection).
    job_ids = []
    with scheduler.scheduler_lock():
        for cfg in task_configs:
            job_id = state.submit_job(cfg.get('name'), cfg,
                                      strategy or 'failover',
                                      max_restarts_on_errors, user)
            _db().execute(
                'UPDATE managed_jobs SET job_group=? WHERE job_id=?',
                (group_name, job_id))
            job_ids.append(job_id)
    scheduler.maybe_schedule_next_jobs()
    return job_ids


def members(group_name: str) -> List[Dict[str, Any]]:
    rows = _db().query(
        'SELECT * FROM managed_jobs WHERE job_group=? ORDER BY job_id',
        (group_name,))
    return [state._decode(r) for r in rows]  # pylint: disable=protected-access


def publish_address(job_id: int, head_ip: str) -> None:
    _db().execute('UPDATE managed_jobs SET head_ip=? WHERE job_id=?',
                  (head_ip, job_id))


def _env_var_for(task_name: str) -> str:
    return ('SKYPILOT_JOBGROUP_ADDR_' +
            re.sub(r'[^A-Za-z0-9]', '_', task_name).upper())


def wait_peer_addresses(group_name: str, my_job_id: int,
                        timeout: float = _PUBLISH_TIMEOUT_SECONDS
                        ) -> Dict[str, str]:
    """Block until every *live* member of the group published an
    address; returns {env_var_name: ip} including our own entry.

    A peer that already failed terminally (e.g. could not get
    capacity) fails the whole group — that is the all-or-nothing
    contract.
    """
    deadline = time.time() + timeout
    while True:
        rows = members(group_name)
        failed = [r for r in rows
                  if r['status'].is_terminal() and
                  r['job_id'] != my_job_id]
        if failed:
            raise exceptions.SkyError(
                f'Job group {group_name!r}: peer '
                f'{failed[0]["name"]!r} already ended '
                f'({failed[0]["status"].value}); aborting group.')
        missing = [r for r in rows if not r.get('head_ip')]
        if not missing:
            return {_env_var_for(r['name']): r['head_ip'] for r in rows}
        if time.time() > deadline:
            raise exceptions.SkyError(
                f'Job group {group_name!r}: peers '
                f'{[r["name"] for r in missing]} did not publish an '
                f'address within {timeout:.0f}s.')
        time.sleep(2.0)


def cancel_group(group_name: str) -> List[int]:
    from skypilot_tpu.jobs import scheduler
    cancelled = []
    for r in members(group_name):
        if not r['status'].is_terminal():
            scheduler.cancel_job(r['job_id'])
            cancelled.append(r['job_id'])
    return cancelled
