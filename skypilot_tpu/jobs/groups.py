"""Job groups: co-scheduled managed jobs that can reach each other.

Reference: sky/jobs/job_group_networking.py:1-21 + the job-group
co-optimization hook (sky/optimizer.py:1796) — N tasks submitted as
one unit (RL actor/learner pairs, disaggregated serving), scheduled
all-or-nothing, each task's env carrying every peer's head address.

Mechanics here: members share a `job_group` tag in the managed-jobs
DB. The scheduler admits the whole group or none. Each member's
controller provisions its cluster, publishes its head's internal IP
to the DB, waits for all peers to publish, then injects

    SKYPILOT_JOBGROUP=<group>
    SKYPILOT_JOBGROUP_ADDR_<TASKNAME>=<ip>   (one per member)

into the task env and submits the user job. On recovery the new
address is re-published; peers observe it by re-resolving at
reconnect time.
"""
from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import ux_utils

_PUBLISH_TIMEOUT_SECONDS = 900.0

# Group/task names end up in hostnames, shell scripts, and file
# paths: restrict to hostname-safe tokens (also prevents shell
# injection via the remote hosts-update script).
_NAME_RE = re.compile(r'^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$')


def _validate_name(kind: str, name: str) -> None:
    if not _NAME_RE.match(name or ''):
        raise exceptions.SkyError(
            f'{kind} {name!r} must be hostname-safe: start with an '
            f'alphanumeric, then [A-Za-z0-9_.-], max 64 chars.')


def hosts_file_path(group_name: str) -> str:
    """The fixed-path hosts file: same absolute path on every host of
    every member cluster (the SKYPILOT_JOBGROUP_HOSTS_FILE value)."""
    return f'/tmp/skypilot-jobgroup-{group_name}.hosts'


def _hosts_begin(group_name: str) -> str:
    # GROUP-SCOPED markers: two groups sharing one /etc/hosts (Local
    # cloud; any shared host) must not wipe each other's blocks.
    return f'# >>> skypilot-jobgroup {group_name} >>>'


def _hosts_end(group_name: str) -> str:
    return f'# <<< skypilot-jobgroup {group_name} <<<'


def _db():
    # job_group/head_ip columns are migrated once in state._db().
    return state._db()  # pylint: disable=protected-access


def launch_group(group_name: str, task_configs: List[Dict[str, Any]],
                 user: str, strategy: Optional[str] = None,
                 max_restarts_on_errors: int = 0) -> List[int]:
    """Atomically submit one managed job per task config.

    Every task needs a unique `name` (it keys the peer-address env
    var). Returns the managed-job ids, all PENDING until the scheduler
    can admit the entire group.
    """
    if not task_configs:
        raise exceptions.SkyError('Job group needs at least one task.')
    _validate_name('Job group name', group_name)
    names = [cfg.get('name') for cfg in task_configs]
    if None in names or len(set(names)) != len(names):
        raise exceptions.SkyError(
            'Every task in a job group needs a unique name; got '
            f'{names}.')
    for name in names:
        _validate_name('Group task name', name)
    from skypilot_tpu.jobs import scheduler
    if len(task_configs) > scheduler.MAX_STARTING_JOBS:
        raise exceptions.SkyError(
            f'Job group {group_name!r} has {len(task_configs)} tasks; '
            f'all-or-nothing admission caps groups at '
            f'{scheduler.MAX_STARTING_JOBS} (the concurrent-start limit).')
    if _db().query_one(
            'SELECT job_id FROM managed_jobs WHERE job_group=? AND status '
            f'NOT IN ({",".join("?" * len(state._TERMINAL))})',  # pylint: disable=protected-access
            (group_name, *(s.value for s in state._TERMINAL))):  # pylint: disable=protected-access
        raise exceptions.SkyError(
            f'Job group {group_name!r} already has active jobs.')
    task_configs = _pin_joint_placement(group_name, task_configs)
    # Insert + tag under the scheduler lock: a concurrent scheduler pass
    # must never observe a member as a plain group-less PENDING job (it
    # would spawn it solo, skipping peer-address injection).
    job_ids = []
    with scheduler.scheduler_lock():
        for cfg in task_configs:
            job_id = state.submit_job(cfg.get('name'), cfg,
                                      strategy or 'failover',
                                      max_restarts_on_errors, user)
            _db().execute(
                'UPDATE managed_jobs SET job_group=? WHERE job_id=?',
                (group_name, job_id))
            job_ids.append(job_id)
    scheduler.maybe_schedule_next_jobs()
    return job_ids


def _pin_joint_placement(group_name: str,
                         task_configs: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    """ONE placement decision for the whole group: pin every member's
    resources to a common cloud+region (reference: sky/optimizer.py:
    1037 SAME_INFRA). Falls back to independent placement (unchanged
    configs) when no common infra exists — the reference's fallback —
    or when the optimizer cannot evaluate the configs (e.g. a pool
    target resolved later)."""
    import copy as copy_lib

    from skypilot_tpu import optimizer as optimizer_lib
    from skypilot_tpu import task as task_lib
    try:
        tasks = [task_lib.Task.from_yaml_config(copy_lib.deepcopy(cfg))
                 for cfg in task_configs]
        infra = optimizer_lib.Optimizer.optimize_group(tasks)
    except Exception as e:  # pylint: disable=broad-except
        ux_utils.log(f'Job group {group_name!r}: joint placement '
                     f'skipped ({e}); members place independently.')
        return task_configs
    if infra is None:
        ux_utils.log(f'Job group {group_name!r}: no common cloud/region '
                     'across members; placing independently.')
        return task_configs
    pinned = []
    for cfg, task in zip(task_configs, tasks):
        cfg = copy_lib.deepcopy(cfg)
        # Replace the member's resources with the CONCRETE joint
        # choice (serialized best_resources): this cleanly collapses
        # any_of/ordered sets to the decided candidate instead of
        # bolting cloud/region keys onto a config that may carry its
        # own 'infra' (which would fail validation at controller
        # start).
        cfg['resources'] = task.best_resources.to_yaml_config()
        pinned.append(cfg)
    return pinned


def members(group_name: str) -> List[Dict[str, Any]]:
    rows = _db().query(
        'SELECT * FROM managed_jobs WHERE job_group=? ORDER BY job_id',
        (group_name,))
    return [state._decode(r) for r in rows]  # pylint: disable=protected-access


def publish_address(job_id: int, head_ip: str) -> None:
    _db().execute('UPDATE managed_jobs SET head_ip=? WHERE job_id=?',
                  (head_ip, job_id))


def _env_var_for(task_name: str) -> str:
    return ('SKYPILOT_JOBGROUP_ADDR_' +
            re.sub(r'[^A-Za-z0-9]', '_', task_name).upper())


def wait_peer_addresses(group_name: str, my_job_id: int,
                        timeout: float = _PUBLISH_TIMEOUT_SECONDS
                        ) -> Dict[str, str]:
    """Block until every *live* member of the group published an
    address; returns {env_var_name: ip} including our own entry.

    A peer that already failed terminally (e.g. could not get
    capacity) fails the whole group — that is the all-or-nothing
    contract.
    """
    deadline = time.time() + timeout
    while True:
        rows = members(group_name)
        failed = [r for r in rows
                  if r['status'].is_terminal() and
                  r['job_id'] != my_job_id]
        if failed:
            raise exceptions.SkyError(
                f'Job group {group_name!r}: peer '
                f'{failed[0]["name"]!r} already ended '
                f'({failed[0]["status"].value}); aborting group.')
        missing = [r for r in rows if not r.get('head_ip')]
        if not missing:
            return {_env_var_for(r['name']): r['head_ip'] for r in rows}
        if time.time() > deadline:
            raise exceptions.SkyError(
                f'Job group {group_name!r}: peers '
                f'{[r["name"] for r in missing]} did not publish an '
                f'address within {timeout:.0f}s.')
        time.sleep(2.0)


def hosts_block(group_name: str) -> str:
    """/etc/hosts-format block mapping every published member to the
    stable names `<task>.<group>` and `<task>` (reference:
    sky/jobs/job_group_networking.py:1-21 — address resolution via
    /etc/hosts injection or native DNS)."""
    lines = [_hosts_begin(group_name)]
    for r in members(group_name):
        if r.get('head_ip'):
            lines.append(f'{r["head_ip"]} {r["name"]}.{group_name} '
                         f'{r["name"]}')
    lines.append(_hosts_end(group_name))
    return '\n'.join(lines) + '\n'


def peer_addresses(group_name: str) -> Dict[str, str]:
    """{env_var: ip} for every member that has published — the
    non-blocking form of wait_peer_addresses (adopted controllers
    rebuild the env from here; the DB survives controller death)."""
    return {_env_var_for(r['name']): r['head_ip']
            for r in members(group_name) if r.get('head_ip')}


def _hosts_update_script(block_b64: str, group_name: str) -> str:
    """Shell that installs (or, with an empty block, removes) the
    managed block on one host.

    - The fixed-path file `/tmp/skypilot-jobgroup-<group>.hosts` is
      ALWAYS written — it is the same absolute path on every host, so
      one cluster-wide SKYPILOT_JOBGROUP_HOSTS_FILE value is valid
      everywhere (per-host /etc/hosts writability can differ).
    - /etc/hosts additionally gets the block when writable (cloud VMs
      run as a sudoer; k8s pods are root in-container), giving real
      resolver-level hostnames.
    - SKYPILOT_HOSTS_FILE overrides the /etc/hosts target (tests).
    - Updates are serialized via flock and rewrite CONTENT (cat >),
      never the inode — /etc/hosts is a bind mount in containers and
      mv would break it; unlocked read-modify-write from two
      concurrently recovering controllers could tear the block.
    - The awk also strips blocks under the LEGACY unscoped markers
      ('# >>> skypilot-jobgroup >>>') so a pre-scoping block cannot
      shadow refreshed entries (the resolver returns the first
      /etc/hosts match).
    """
    # group_name is validated hostname-safe (launch_group), so the
    # f-string interpolations below cannot break out of the script —
    # but '.' and '-' are legal in names and '.' is a regex wildcard,
    # so every ERE metacharacter must be escaped or group 'a.b' would
    # also strip group 'aXb''s managed block.
    def awk_escape(s: str) -> str:
        return re.sub(r'([\\/.\[\](){}*+?|^$])', r'\\\1', s)

    begin = awk_escape(_hosts_begin(group_name))
    end = awk_escape(_hosts_end(group_name))
    return f'''
set -e
b64='{block_b64}'
update() {{
  f="$1"
  [ -e "$f" ] || touch "$f" 2>/dev/null || return 1
  [ -w "$f" ] || return 1
  awk '/{begin}/{{skip=1}} /# >>> skypilot-jobgroup >>>/{{skip=1}} !skip{{print}} /{end}/{{skip=0}}  /# <<< skypilot-jobgroup <<</{{skip=0}}' "$f" > "$f.skytmp" || return 1
  if [ -n "$b64" ]; then printf %s "$b64" | base64 -d >> "$f.skytmp"; fi
  cat "$f.skytmp" > "$f" && rm -f "$f.skytmp"
}}
run_locked() {{
  if command -v flock >/dev/null 2>&1; then
    flock 9
  fi
  fixed='{hosts_file_path(group_name)}'
  if [ -n "$b64" ]; then
    update "$fixed"
    echo "installed:$fixed"
  else
    rm -f "$fixed"
  fi
  target="${{SKYPILOT_HOSTS_FILE:-/etc/hosts}}"
  if update "$target"; then echo "installed:$target"; fi
  true
}}
run_locked 9> /tmp/.skypilot-jobgroup-hosts.lock
'''


def install_hosts_entries(handle, group_name: str,
                          max_attempts: int = 3) -> str:
    """Install the group's hosts block on every host of a member
    cluster (parallel fan-out, per-host retries); returns the
    cluster-wide path for SKYPILOT_JOBGROUP_HOSTS_FILE.

    Raises only after `max_attempts` failures on some host — callers
    on the launch path degrade gracefully (peer env addresses remain
    the source of truth; hostnames are convenience).
    """
    import base64

    from skypilot_tpu.utils import subprocess_utils
    block_b64 = base64.b64encode(
        hosts_block(group_name).encode()).decode()
    script = _hosts_update_script(block_b64, group_name)
    landing = hosts_file_path(group_name)

    def _one(runner) -> None:
        # Jittered backoff PER HOST: after a zone-wide preemption
        # every relaunching member retries hosts injection at once,
        # and linear lockstep sleeps re-collide the whole herd on the
        # shared /etc/hosts lock each round.
        from skypilot_tpu.utils import common_utils
        backoff = common_utils.Backoff(1.0, max_backoff=8.0,
                                       jitter=True)
        last_err = ''
        for attempt in range(max_attempts):
            rc, _, err = runner.run(script, require_outputs=True)
            if rc == 0:
                return
            last_err = err[-300:]
            time.sleep(backoff.current_backoff())
        raise exceptions.SkyError(
            f'Job group {group_name!r}: hosts injection failed on '
            f'{runner!r} after {max_attempts} attempts: {last_err}')

    subprocess_utils.run_in_parallel(_one, handle.get_command_runners())
    # The fixed path is the cluster-wide contract: same absolute path
    # on every host regardless of per-host /etc/hosts writability.
    return landing


def remove_hosts_entries(handle, group_name: str) -> None:
    """Best-effort removal of the managed block + fixed-path file on
    every host (cleanup when a member ends; pool workers are REUSED,
    so stale name->IP mappings must not leak into the next job)."""
    from skypilot_tpu.utils import subprocess_utils
    script = _hosts_update_script('', group_name)

    def _one(runner) -> None:
        try:
            runner.run(script, require_outputs=True)
        except Exception as e:  # pylint: disable=broad-except
            # One unreachable host must not block the others' cleanup,
            # but a stale mapping on a reused worker is worth a line.
            ux_utils.log(f'Job group {group_name!r}: hosts cleanup on '
                         f'one host failed ({e}).')

    try:
        subprocess_utils.run_in_parallel(_one,
                                         handle.get_command_runners())
    except Exception as e:  # pylint: disable=broad-except
        ux_utils.log(f'Job group {group_name!r}: hosts cleanup '
                     f'skipped ({e}).')


def cancel_group(group_name: str) -> List[int]:
    from skypilot_tpu.jobs import scheduler
    cancelled = []
    for r in members(group_name):
        if not r['status'].is_terminal():
            scheduler.cancel_job(r['job_id'])
            cancelled.append(r['job_id'])
    return cancelled
