"""Managed-jobs state: sqlite table + status machine.

Reference: sky/jobs/state.py (3621 LoC) — ManagedJobStatus enum
(:467) and the `spot`/`job_info` tables. One table here; the schedule
state is a column, not a daemon (reference scheduler docstring,
sky/jobs/scheduler.py:1-43).
"""
from __future__ import annotations

import enum
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu.utils import db_utils


class ManagedJobStatus(enum.Enum):
    PENDING = 'PENDING'
    SUBMITTED = 'SUBMITTED'
    STARTING = 'STARTING'
    RUNNING = 'RUNNING'
    # The trainer announced a typed recoverable exit (graceful
    # preemption checkpoint or watchdog abort): transitional state
    # between the typed agent-job status landing and recovery
    # starting — PREEMPTING -> RECOVERING -> RUNNING.
    PREEMPTING = 'PREEMPTING'
    RECOVERING = 'RECOVERING'
    CANCELLING = 'CANCELLING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_PRECHECKS = 'FAILED_PRECHECKS'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    def is_failed(self) -> bool:
        return self in (ManagedJobStatus.FAILED,
                        ManagedJobStatus.FAILED_SETUP,
                        ManagedJobStatus.FAILED_PRECHECKS,
                        ManagedJobStatus.FAILED_NO_RESOURCE,
                        ManagedJobStatus.FAILED_CONTROLLER)


_TERMINAL = {
    ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
    ManagedJobStatus.FAILED_SETUP, ManagedJobStatus.FAILED_PRECHECKS,
    ManagedJobStatus.FAILED_NO_RESOURCE, ManagedJobStatus.FAILED_CONTROLLER,
    ManagedJobStatus.CANCELLED,
}

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS managed_jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT,
    task_config TEXT,
    status TEXT,
    cluster_name TEXT,
    submitted_at REAL,
    started_at REAL,
    ended_at REAL,
    recovery_count INTEGER DEFAULT 0,
    max_restarts_on_errors INTEGER DEFAULT 0,
    strategy TEXT,
    last_error TEXT,
    controller_pid INTEGER DEFAULT -1,
    user TEXT,
    log_path TEXT
);
"""


@functools.lru_cache(maxsize=None)
def _db_for(path: str) -> db_utils.SQLiteDB:
    return db_utils.open_db(path, _CREATE_SQL)


@functools.lru_cache(maxsize=None)
def _migrated_db_for(path: str) -> db_utils.SQLiteDB:
    """One-time-per-process schema migration (controllers poll state
    every few seconds; PRAGMA scans must not run per query)."""
    db = _db_for(path)
    for column, decl in (
            # HA columns (controller crash recovery):
            ('agent_job_id', 'INTEGER DEFAULT -1'),
            ('adopt_attempts', 'INTEGER DEFAULT 0'),
            # Job groups:
            ('job_group', 'TEXT'),
            ('head_ip', 'TEXT'),
            # Pipelines (multi-stage managed jobs):
            ('stage', 'INTEGER DEFAULT 0'),
            # Pools:
            ('pool', 'TEXT'),
            ('pool_worker', 'TEXT')):
        db.add_column_if_missing('managed_jobs', column, decl)
    # Per-recovery-event timestamps: the fleet bench and the
    # dashboard compute recovery latency from these instead of
    # scraping controller logs. One row per detected preemption;
    # recovered_at stays NULL while recovery is in flight (or if it
    # never completes).
    db.execute("""\
CREATE TABLE IF NOT EXISTS recovery_events (
    event_id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_id INTEGER,
    zone TEXT,
    preempted_at REAL,
    recovered_at REAL
)""")
    return db


def _db() -> db_utils.SQLiteDB:
    return _migrated_db_for(os.path.join(constants.sky_home(),
                                         'managed_jobs.db'))


def submit_job(name: Optional[str], task_config: Dict[str, Any],
               strategy: str, max_restarts_on_errors: int,
               user: str, pool: Optional[str] = None) -> int:
    db = _db()
    with db.conn() as conn:
        cur = conn.execute(
            'INSERT INTO managed_jobs (name, task_config, status, '
            'submitted_at, strategy, max_restarts_on_errors, user, pool) '
            'VALUES (?,?,?,?,?,?,?,?)',
            (name, json.dumps(task_config),
             ManagedJobStatus.PENDING.value, time.time(), strategy,
             max_restarts_on_errors, user, pool))
        job_id = int(cur.lastrowid)
    log_dir = os.path.join(constants.sky_home(), 'managed_jobs_logs')
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f'{job_id}.log')
    db.execute('UPDATE managed_jobs SET log_path=?, cluster_name=? '
               'WHERE job_id=?',
               (log_path, f'managed-{job_id}', job_id))
    return job_id


def assign_pool_worker(job_id: int, worker_cluster: str) -> None:
    _db().execute(
        'UPDATE managed_jobs SET pool_worker=?, cluster_name=? '
        'WHERE job_id=?', (worker_cluster, worker_cluster, job_id))


def _decode(row: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(row)
    out['status'] = ManagedJobStatus(out['status'])
    out['task_config'] = (json.loads(out['task_config'])
                          if out['task_config'] else {})
    return out


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM managed_jobs WHERE job_id=?',
                          (job_id,))
    return _decode(row) if row else None


def get_jobs(status: Optional[List[ManagedJobStatus]] = None
             ) -> List[Dict[str, Any]]:
    sql = 'SELECT * FROM managed_jobs'
    params: tuple = ()
    if status:
        marks = ','.join('?' * len(status))
        sql += f' WHERE status IN ({marks})'
        params = tuple(s.value for s in status)
    sql += ' ORDER BY job_id'
    return [_decode(r) for r in _db().query(sql, params)]


def set_status(job_id: int, status: ManagedJobStatus,
               last_error: Optional[str] = None) -> None:
    sets = ['status=?']
    params: List[Any] = [status.value]
    if status == ManagedJobStatus.RUNNING:
        sets.append('started_at=COALESCE(started_at, ?)')
        params.append(time.time())
    if status.is_terminal():
        sets.append('ended_at=?')
        params.append(time.time())
    if last_error is not None:
        sets.append('last_error=?')
        params.append(last_error[-2000:])
    params.append(job_id)
    _db().execute(
        f'UPDATE managed_jobs SET {", ".join(sets)} WHERE job_id=?',
        tuple(params))


def set_controller_pid(job_id: int, pid: int) -> None:
    _db().execute('UPDATE managed_jobs SET controller_pid=? WHERE job_id=?',
                  (pid, job_id))


def set_agent_job_id(job_id: int, agent_job_id: int) -> None:
    """Persist the controller's intent: which on-cluster job it watches.

    This is what lets a respawned controller re-adopt a running job
    after a crash instead of relaunching it (reference:
    sky/jobs/managed_job_refresh_thread.py)."""
    _db().execute('UPDATE managed_jobs SET agent_job_id=? WHERE job_id=?',
                  (agent_job_id, job_id))


def bump_adopt_attempts(job_id: int) -> int:
    _db().execute('UPDATE managed_jobs SET adopt_attempts='
                  'adopt_attempts+1 WHERE job_id=?', (job_id,))
    row = _db().query_one('SELECT adopt_attempts FROM managed_jobs '
                          'WHERE job_id=?', (job_id,))
    return int(row['adopt_attempts']) if row else 0


def set_stage(job_id: int, stage: int) -> None:
    """Pipelines: persist which stage the controller is executing so a
    re-adopted controller resumes mid-pipeline."""
    _db().execute('UPDATE managed_jobs SET stage=? WHERE job_id=?',
                  (stage, job_id))


def reset_adopt_attempts(job_id: int) -> None:
    """Called after a SUCCESSFUL re-adoption: only consecutive failed
    adoptions count toward giving up, not controller deaths spread over
    a long job's lifetime."""
    _db().execute('UPDATE managed_jobs SET adopt_attempts=0 '
                  'WHERE job_id=?', (job_id,))


def bump_recovery(job_id: int) -> int:
    _db().execute('UPDATE managed_jobs SET recovery_count=recovery_count+1 '
                  'WHERE job_id=?', (job_id,))
    row = _db().query_one('SELECT recovery_count FROM managed_jobs '
                          'WHERE job_id=?', (job_id,))
    return int(row['recovery_count']) if row else 0


def record_preemption(job_id: int, zone: Optional[str]) -> int:
    """Open a recovery event at detection time (the controller's
    grace window just expired, or an external source reported the
    cluster failed). Returns the event id."""
    db = _db()
    with db.conn() as conn:
        cur = conn.execute(
            'INSERT INTO recovery_events (job_id, zone, preempted_at) '
            'VALUES (?,?,?)', (job_id, zone, time.time()))
        return int(cur.lastrowid)


def record_recovered(job_id: int) -> None:
    """Close the job's most recent open recovery event (the relaunch
    succeeded and the job is RUNNING again)."""
    _db().execute(
        'UPDATE recovery_events SET recovered_at=? WHERE event_id='
        '(SELECT event_id FROM recovery_events WHERE job_id=? AND '
        'recovered_at IS NULL ORDER BY event_id DESC LIMIT 1)',
        (time.time(), job_id))


def get_recovery_events(job_id: Optional[int] = None
                        ) -> List[Dict[str, Any]]:
    """Recovery events, oldest first ({event_id, job_id, zone,
    preempted_at, recovered_at}); all jobs when job_id is None."""
    sql = 'SELECT * FROM recovery_events'
    params: tuple = ()
    if job_id is not None:
        sql += ' WHERE job_id=?'
        params = (job_id,)
    sql += ' ORDER BY event_id'
    return [dict(r) for r in _db().query(sql, params)]


def status_counts() -> Dict[str, int]:
    """{status: count} aggregate (metrics path)."""
    rows = _db().query(
        'SELECT status, COUNT(*) AS n FROM managed_jobs GROUP BY status')
    return {r['status'].lower(): int(r['n']) for r in rows if r['status']}
