"""Managed-jobs scheduler: not a daemon — called on every state change.

Reference: sky/jobs/scheduler.py docstring (:1-43): scheduling
decisions happen in `maybe_schedule_next_jobs()`, invoked at submit
time and when a controller finishes; limits bound concurrently
launching and running jobs. State lives only in the DB.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

from skypilot_tpu import constants
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import locks
from skypilot_tpu.utils import subprocess_utils

# Env-overridable for fleet-scale deployments (the defaults assume a
# laptop-class controller host; a dedicated controller VM happily
# runs hundreds of monitor processes).
MAX_STARTING_JOBS = int(
    os.environ.get('SKYPILOT_JOBS_MAX_STARTING', '4'))
MAX_RUNNING_JOBS = int(
    os.environ.get('SKYPILOT_JOBS_MAX_RUNNING', '200'))


_MAX_ADOPT_ATTEMPTS = 3


def scheduler_lock() -> locks.FileLock:
    return locks.FileLock(os.path.join(constants.sky_home(),
                                       'jobs_scheduler.lock'))


def maybe_schedule_next_jobs() -> None:
    """Spawn controllers for PENDING jobs within limits.

    Job groups are admitted all-or-nothing: either every PENDING
    member of a group fits in the remaining start budget and they all
    spawn together, or none do (reference: job-group co-scheduling,
    sky/optimizer.py:1796)."""
    with scheduler_lock():
        _reconcile_dead_controllers()
        starting = len(state.get_jobs(status=[
            state.ManagedJobStatus.SUBMITTED,
            state.ManagedJobStatus.STARTING,
            state.ManagedJobStatus.PREEMPTING,
            state.ManagedJobStatus.RECOVERING]))
        running = len(state.get_jobs(status=[
            state.ManagedJobStatus.RUNNING]))
        pending = state.get_jobs(status=[state.ManagedJobStatus.PENDING])
        skipped_groups = set()
        for job in pending:
            budget = min(MAX_STARTING_JOBS - starting,
                         MAX_RUNNING_JOBS - starting - running)
            if budget <= 0:
                break
            group = job.get('job_group')
            if group:
                if group in skipped_groups:
                    continue
                members = [j for j in pending
                           if j.get('job_group') == group]
                if len(members) > budget:
                    skipped_groups.add(group)
                    continue  # group doesn't fit yet: all-or-nothing
                for member in members:
                    _spawn_controller(member)
                    starting += 1
                skipped_groups.add(group)  # spawned; don't revisit
                continue
            if job.get('pool'):
                from skypilot_tpu.jobs import pools as pools_lib
                worker = pools_lib.assign_worker(job['pool'])
                if worker is None:
                    continue  # pool saturated; stays PENDING
                state.assign_pool_worker(job['job_id'], worker)
            _spawn_controller(job)
            starting += 1


def _spawn_controller(job, adopt: bool = False) -> None:
    job_id = job['job_id']
    if not adopt:
        state.set_status(job_id, state.ManagedJobStatus.SUBMITTED)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f'{repo_root}:{env.get("PYTHONPATH", "")}'
    cmd = [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
           '--job-id', str(job_id)]
    if adopt:
        cmd.append('--adopt')
    pid = subprocess_utils.launch_daemon(
        cmd,
        log_path=job['log_path'] or os.path.join(
            constants.sky_home(), f'managed-{job_id}.log'),
        env=env)
    state.set_controller_pid(job_id, pid)


def _reconcile_dead_controllers() -> None:
    """HA: a dead controller with a live job is re-adopted, not failed.

    A fresh controller re-attaches to the recorded (cluster, agent job)
    and resumes monitoring; only after repeated adoption failures does
    the job fail (reference: sky/jobs/managed_job_refresh_thread.py).
    """
    active = state.get_jobs(status=[
        state.ManagedJobStatus.SUBMITTED, state.ManagedJobStatus.STARTING,
        state.ManagedJobStatus.RUNNING, state.ManagedJobStatus.PREEMPTING,
        state.ManagedJobStatus.RECOVERING,
        state.ManagedJobStatus.CANCELLING])
    for job in active:
        pid = job.get('controller_pid') or -1
        if pid > 0 and not subprocess_utils.process_alive(pid):
            attempts = state.bump_adopt_attempts(job['job_id'])
            if attempts > _MAX_ADOPT_ATTEMPTS:
                state.set_status(
                    job['job_id'], state.ManagedJobStatus.FAILED_CONTROLLER,
                    last_error=f'controller died {attempts} times; '
                               'giving up re-adoption')
                continue
            from skypilot_tpu.utils import ux_utils
            ux_utils.log(f'Managed job {job["job_id"]}: controller '
                         f'(pid {pid}) died; re-adopting '
                         f'(attempt {attempts}/{_MAX_ADOPT_ATTEMPTS}).')
            _spawn_controller(job, adopt=True)


def cancel_job(job_id: int) -> bool:
    job = state.get_job(job_id)
    if job is None or job['status'].is_terminal():
        return False
    if job['status'] == state.ManagedJobStatus.PENDING:
        state.set_status(job_id, state.ManagedJobStatus.CANCELLED)
        return True
    state.set_status(job_id, state.ManagedJobStatus.CANCELLING)
    pid = job.get('controller_pid') or -1
    if pid > 0:
        # SIGTERM only the controller itself: its handler cancels the
        # agent job and tears the cluster down gracefully.
        import signal
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            state.set_status(job_id, state.ManagedJobStatus.CANCELLED)
    return True
