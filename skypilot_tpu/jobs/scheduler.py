"""Managed-jobs scheduler: not a daemon — called on every state change.

Reference: sky/jobs/scheduler.py docstring (:1-43): scheduling
decisions happen in `maybe_schedule_next_jobs()`, invoked at submit
time and when a controller finishes; limits bound concurrently
launching and running jobs. State lives only in the DB.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

from skypilot_tpu import constants
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import locks
from skypilot_tpu.utils import subprocess_utils

MAX_STARTING_JOBS = 4
MAX_RUNNING_JOBS = 200


def maybe_schedule_next_jobs() -> None:
    """Spawn controllers for PENDING jobs within limits."""
    with locks.FileLock(os.path.join(constants.sky_home(),
                                     'jobs_scheduler.lock')):
        _reconcile_dead_controllers()
        starting = len(state.get_jobs(status=[
            state.ManagedJobStatus.SUBMITTED,
            state.ManagedJobStatus.STARTING,
            state.ManagedJobStatus.RECOVERING]))
        running = len(state.get_jobs(status=[
            state.ManagedJobStatus.RUNNING]))
        pending = state.get_jobs(status=[state.ManagedJobStatus.PENDING])
        for job in pending:
            if starting >= MAX_STARTING_JOBS or \
                    starting + running >= MAX_RUNNING_JOBS:
                break
            if job.get('pool'):
                from skypilot_tpu.jobs import pools as pools_lib
                worker = pools_lib.assign_worker(job['pool'])
                if worker is None:
                    continue  # pool saturated; stays PENDING
                state.assign_pool_worker(job['job_id'], worker)
            _spawn_controller(job)
            starting += 1


def _spawn_controller(job) -> None:
    job_id = job['job_id']
    state.set_status(job_id, state.ManagedJobStatus.SUBMITTED)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f'{repo_root}:{env.get("PYTHONPATH", "")}'
    pid = subprocess_utils.launch_daemon(
        [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
         '--job-id', str(job_id)],
        log_path=job['log_path'] or os.path.join(
            constants.sky_home(), f'managed-{job_id}.log'),
        env=env)
    state.set_controller_pid(job_id, pid)


def _reconcile_dead_controllers() -> None:
    """Controller crash safety: dead controller + live status → failed.

    Reference: HA recovery (sky/jobs/ controller crash recovery).
    """
    active = state.get_jobs(status=[
        state.ManagedJobStatus.SUBMITTED, state.ManagedJobStatus.STARTING,
        state.ManagedJobStatus.RUNNING, state.ManagedJobStatus.RECOVERING,
        state.ManagedJobStatus.CANCELLING])
    for job in active:
        pid = job.get('controller_pid') or -1
        if pid > 0 and not subprocess_utils.process_alive(pid):
            state.set_status(job['job_id'],
                             state.ManagedJobStatus.FAILED_CONTROLLER,
                             last_error='controller process died')


def cancel_job(job_id: int) -> bool:
    job = state.get_job(job_id)
    if job is None or job['status'].is_terminal():
        return False
    if job['status'] == state.ManagedJobStatus.PENDING:
        state.set_status(job_id, state.ManagedJobStatus.CANCELLED)
        return True
    state.set_status(job_id, state.ManagedJobStatus.CANCELLING)
    pid = job.get('controller_pid') or -1
    if pid > 0:
        # SIGTERM only the controller itself: its handler cancels the
        # agent job and tears the cluster down gracefully.
        import signal
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            state.set_status(job_id, state.ManagedJobStatus.CANCELLED)
    return True
