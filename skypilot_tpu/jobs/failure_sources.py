"""External cluster-failure sources for managed jobs.

Reference: sky/utils/plugin_extensions ExternalClusterFailure,
imported by sky/jobs/controller.py:54-55 — external systems (cloud
health monitors, maintenance schedulers, capacity brokers) declare a
cluster failed so the controller recovers IMMEDIATELY instead of
waiting out probe timeouts and the unreachable grace window.

Config:

    jobs:
      failure_sources:
        - my_plugin.module.check   # importable callable

Each callable takes no arguments and returns an iterable of failed
clusters — either names or {'cluster': name, 'reason': text} dicts.
Sources are polled every monitor tick; a broken source is logged and
isolated (it must never take the controller down), and a source that
fails repeatedly keeps being retried (the external system may be
restarting).
"""
from __future__ import annotations

import importlib
import threading
from typing import Callable, Dict, List, Optional

from skypilot_tpu.utils import ux_utils

_lock = threading.Lock()
_cache: Optional[List[Callable]] = None


def _load_sources() -> List[Callable]:
    """Resolve configured source callables (memoized; a controller is
    one process per job, so config changes apply on its next spawn)."""
    global _cache
    with _lock:
        if _cache is not None:
            return _cache
        from skypilot_tpu import sky_config
        paths = sky_config.get_nested(('jobs', 'failure_sources'),
                                      []) or []
        sources: List[Callable] = []
        for path in paths:
            try:
                module_name, attr = str(path).rsplit('.', 1)
                fn = getattr(importlib.import_module(module_name), attr)
                if not callable(fn):
                    raise TypeError(f'{path} is not callable')
                sources.append(fn)
            except Exception as e:  # pylint: disable=broad-except
                ux_utils.log(f'jobs.failure_sources: skipping '
                             f'{path!r}: {e!r}')
        _cache = sources
        return sources


def reset() -> None:
    """Drop the memoized sources (tests)."""
    global _cache
    with _lock:
        _cache = None


def check_failed(cluster_name: str) -> Optional[str]:
    """Ask every configured source whether `cluster_name` is failed;
    returns the first reported reason, else None. Never raises."""
    for fn in _load_sources():
        try:
            for item in (fn() or ()):
                if isinstance(item, dict):
                    name = item.get('cluster')
                    reason = item.get('reason', 'external source')
                else:
                    name, reason = item, 'external source'
                if name == cluster_name:
                    return str(reason)
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'jobs.failure_sources: source {fn!r} '
                         f'failed: {e!r}')
    return None
