"""Managed-jobs API routes (mounted by server/server.py).

Reference: sky/jobs/server/ (REST under /jobs/*).
"""
from __future__ import annotations

from aiohttp import web

from skypilot_tpu.agent import log_lib
from skypilot_tpu.server.route_utils import scheduled_handler, stream_lines

_API = 'skypilot_tpu.jobs.core'


def _schedule(name: str, entrypoint: str, schedule_type: str = 'short'):
    return scheduled_handler(name, entrypoint, schedule_type)


async def jobs_logs(request: web.Request) -> web.StreamResponse:
    """Stream a managed job's controller log."""
    from skypilot_tpu.jobs import core
    job_id = int(request.query.get('job_id', 0))
    follow = request.query.get('follow', '1') == '1'
    try:
        log_path = core.get_log_path(job_id)
    except Exception as e:  # pylint: disable=broad-except
        return web.json_response(
            {'error': f'no managed job {job_id}: {e}'}, status=404)
    return await stream_lines(
        request,
        lambda: log_lib.tail_logs(
            log_path, follow=follow,
            stop_condition=lambda: core.is_terminal(job_id)))


def register(app: web.Application) -> None:
    app.router.add_post('/jobs/launch',
                        _schedule('jobs.launch', f'{_API}.launch', 'long'))
    app.router.add_post('/jobs/queue',
                        _schedule('jobs.queue', f'{_API}.queue'))
    app.router.add_post('/jobs/cancel',
                        _schedule('jobs.cancel', f'{_API}.cancel'))
    app.router.add_post('/jobs/pool/apply',
                        _schedule('jobs.pool_apply', f'{_API}.pool_apply',
                                  'long'))
    app.router.add_post('/jobs/pool/ls',
                        _schedule('jobs.pool_ls', f'{_API}.pool_ls'))
    app.router.add_post('/jobs/pool/down',
                        _schedule('jobs.pool_down', f'{_API}.pool_down',
                                  'long'))
    app.router.add_post('/jobs/pool/status',
                        _schedule('jobs.pool_status',
                                  f'{_API}.pool_status'))
    app.router.add_post('/jobs/group/launch',
                        _schedule('jobs.group_launch',
                                  f'{_API}.group_launch', 'long'))
    app.router.add_post('/jobs/group/status',
                        _schedule('jobs.group_status',
                                  f'{_API}.group_status'))
    app.router.add_post('/jobs/group/cancel',
                        _schedule('jobs.group_cancel',
                                  f'{_API}.group_cancel', 'long'))
    app.router.add_get('/jobs/logs', jobs_logs)
