"""Managed jobs: preemption-recovering job layer (reference: sky/jobs/)."""
