"""LoRA (low-rank adaptation) for the Llama-family models.

One module, three consumers:

  - TRAINING (`train_lm --lora RANK`, parallel/train.py): the base
    params are frozen and only the per-projection A/B factors train.
    The model applies `y = Wx + (alpha/rank) * B^T A^T x` inside the
    forward pass (single-adapter mode: 2-D factors, no per-row
    gather), so the guard / checkpoint / ZeRO machinery sees a
    normal params pytree `{'base': ..., 'lora': ...}`.
  - SERVING (inference/adapters.py + models/batching.py): adapters
    live device-resident as STACKED `[n_slots_of_adapters, d, r]`
    factors; every engine decode slot carries an `adapter_id` row
    index and the forward gathers each row's factors into a batched
    matmul — one dispatch serves many adapters. Row 0 is all-zeros
    (the base model), so base and adapter requests share a round.
  - ARTIFACTS: `save_adapter`/`load_adapter` write and read the
    on-disk format (`adapter_config.json` + `adapter_weights.npz`)
    that `train_lm --lora` produces and the serving registry loads
    unmodified — the produce-then-serve loop.

Factor orientation matches the flax Dense kernels they adapt:
`a: [d_in, rank]`, `b: [rank, d_out]`, delta `W' = W + a @ b * scale`
with `scale = alpha / rank`. `a` initializes from a small normal and
`b` from zeros, so step 0 is exactly the base model.
"""
from __future__ import annotations

import dataclasses
import inspect
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

ATTN_TARGETS: Tuple[str, ...] = ('wq', 'wk', 'wv', 'wo')
MLP_TARGETS: Tuple[str, ...] = ('w_gate', 'w_up', 'w_down')
ALL_TARGETS: Tuple[str, ...] = ATTN_TARGETS + MLP_TARGETS

#: Which Block submodule owns each projection (merge_lora walks the
#: real param tree with this).
_TARGET_MODULE = {t: 'attn' for t in ATTN_TARGETS}
_TARGET_MODULE.update({t: 'mlp' for t in MLP_TARGETS})

CONFIG_FILE = 'adapter_config.json'
WEIGHTS_FILE = 'adapter_weights.npz'


@dataclasses.dataclass(frozen=True)
class LoraSpec:
    """Rank/alpha/target-set of one adapter (or one training run)."""
    rank: int
    alpha: float
    targets: Tuple[str, ...] = ATTN_TARGETS

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f'lora rank must be >= 1, got {self.rank}')
        unknown = [t for t in self.targets if t not in ALL_TARGETS]
        if unknown:
            raise ValueError(
                f'unknown lora targets {unknown}; valid: {ALL_TARGETS}')

    @property
    def scale(self) -> float:
        return float(self.alpha) / float(self.rank)


def targets_from_name(name: str) -> Tuple[str, ...]:
    """CLI sugar: 'attn' | 'attn-mlp'/'all' -> target tuple."""
    if name == 'attn':
        return ATTN_TARGETS
    if name in ('attn-mlp', 'all'):
        return ALL_TARGETS
    if name == 'mlp':
        return MLP_TARGETS
    raise ValueError(f'unknown lora target set {name!r} '
                     f'(use attn | mlp | attn-mlp)')


def supports(model) -> bool:
    """True when `model` threads the `lora` kwarg through its forward
    pass AND its config exposes the Llama-family projection geometry
    (`projection_shapes` below). Dequant-on-read wrappers
    (inference/quant.py QuantizedModel) are unwrapped: LoRA deltas
    apply to projection OUTPUTS, so they ride the dequantized base
    unchanged."""
    model = getattr(model, 'base_model', model)
    try:
        sig = inspect.signature(type(model).__call__)
    except (TypeError, ValueError):
        return False
    if 'lora' not in sig.parameters:
        return False
    try:
        projection_shapes(model.config)
    except (AttributeError, ValueError):
        return False
    return True


def projection_shapes(cfg) -> Dict[str, Tuple[int, int]]:
    """(d_in, d_out) per adaptable projection for a Llama-family
    config (llama / qwen tiers share the geometry)."""
    hd = cfg.embed_dim // cfg.num_heads
    return {
        'wq': (cfg.embed_dim, cfg.num_heads * hd),
        'wk': (cfg.embed_dim, cfg.num_kv_heads * hd),
        'wv': (cfg.embed_dim, cfg.num_kv_heads * hd),
        'wo': (cfg.num_heads * hd, cfg.embed_dim),
        'w_gate': (cfg.embed_dim, cfg.mlp_dim),
        'w_up': (cfg.embed_dim, cfg.mlp_dim),
        'w_down': (cfg.mlp_dim, cfg.embed_dim),
    }


def adapter_num_bytes(cfg, rank: int, targets: Tuple[str, ...],
                      bytes_per_elem: int = 4) -> int:
    """Device bytes ONE adapter occupies in the stacked store — the
    memory-budget math behind `--max-adapters` (docs/guides.md)."""
    shapes = projection_shapes(cfg)
    per_layer = sum((d_in + d_out) * rank
                    for t, (d_in, d_out) in shapes.items()
                    if t in targets)
    return per_layer * cfg.num_layers * bytes_per_elem


# -- parameter construction -------------------------------------------------
def init_lora_params(rng, cfg, spec: LoraSpec):
    """Fresh trainable factors: a ~ N(0, 0.02), b = 0 (step 0 == base
    model). Layout: {'layer_i': {target: {'a': [d_in, r],
    'b': [r, d_out]}}} in f32 (the trained dtype)."""
    import jax
    import jax.numpy as jnp
    shapes = projection_shapes(cfg)
    params: Dict[str, Any] = {}
    for i in range(cfg.num_layers):
        layer: Dict[str, Any] = {}
        for t in spec.targets:
            d_in, d_out = shapes[t]
            rng, sub = jax.random.split(rng)
            layer[t] = {
                'a': jax.random.normal(sub, (d_in, spec.rank),
                                       jnp.float32) * 0.02,
                'b': jnp.zeros((spec.rank, d_out), jnp.float32),
            }
        params[f'layer_{i}'] = layer
    return params


def random_adapter_params(seed: int, cfg, spec: LoraSpec
                          ) -> Dict[str, Any]:
    """Numpy-only random adapter (BOTH factors non-zero, so the delta
    is non-trivial) — benchmark/test artifact generation without
    touching the training path."""
    rng = np.random.default_rng(seed)
    shapes = projection_shapes(cfg)
    params: Dict[str, Any] = {}
    for i in range(cfg.num_layers):
        layer: Dict[str, Any] = {}
        for t in spec.targets:
            d_in, d_out = shapes[t]
            layer[t] = {
                'a': rng.normal(0, 0.02, (d_in, spec.rank)
                                ).astype(np.float32),
                'b': rng.normal(0, 0.02, (spec.rank, d_out)
                                ).astype(np.float32),
            }
        params[f'layer_{i}'] = layer
    return params


def as_model_lora(lora_params, scale):
    """Wrap raw per-layer factors into the pytree the model forward
    consumes: {'scale': f32 scalar, 'layers': {...}}."""
    import jax.numpy as jnp
    return {'scale': jnp.asarray(scale, jnp.float32),
            'layers': lora_params}


def apply_delta(y, x, factors, adapter_ids, scale):
    """y + scale * ((x @ a) @ b), computed in f32.

    Single-adapter mode (`adapter_ids is None`): `a: [d_in, r]`,
    `b: [r, d_out]` apply to every row — the training path.

    Batched mode: `a: [N, d_in, r]`, `b: [N, r, d_out]` stacked per
    device adapter slot; `adapter_ids: [batch]` gathers each row's
    factors into a batched matmul, so one dispatch serves many
    adapters (row 0 is all-zeros = the base model).
    """
    import jax.numpy as jnp
    a, b = factors['a'], factors['b']
    xf = x.astype(jnp.float32)
    if adapter_ids is None:
        h = jnp.einsum('bsd,dr->bsr', xf, a.astype(jnp.float32))
        delta = jnp.einsum('bsr,ro->bso', h, b.astype(jnp.float32))
    else:
        ai = a[adapter_ids].astype(jnp.float32)     # [B, d_in, r]
        bi = b[adapter_ids].astype(jnp.float32)     # [B, r, d_out]
        h = jnp.einsum('bsd,bdr->bsr', xf, ai)
        delta = jnp.einsum('bsr,bro->bso', h, bi)
    return y + (scale * delta).astype(y.dtype)


def merge_lora(params, lora_params, spec: LoraSpec):
    """Merged-weights copy of `params`: every adapted kernel becomes
    W + a @ b * scale. The parity oracle — batched per-slot LoRA in
    the engine must reproduce this forward exactly (fp32 tolerance);
    also the zero-serving-overhead deployment form for ONE adapter."""
    import jax
    import jax.numpy as jnp
    merged = jax.tree.map(lambda x: x, params)  # shallow-ish copy
    for layer_name, layer in lora_params.items():
        for t, factors in layer.items():
            module = _TARGET_MODULE[t]
            kern = merged[layer_name][module][t]['kernel']
            delta = (jnp.asarray(factors['a'], jnp.float32) @
                     jnp.asarray(factors['b'], jnp.float32)) * spec.scale
            merged[layer_name][module][t]['kernel'] = (
                kern.astype(jnp.float32) + delta).astype(kern.dtype)
    return merged


# -- artifacts --------------------------------------------------------------
def save_adapter(out_dir: str, lora_params, spec: LoraSpec, *,
                 base_model: str, step: Optional[int] = None) -> str:
    """Write the adapter artifact the serving registry loads
    unmodified: `adapter_config.json` + `adapter_weights.npz`
    (flattened `layer_i/target/a|b` keys)."""
    os.makedirs(out_dir, exist_ok=True)
    flat: Dict[str, np.ndarray] = {}
    for layer_name, layer in lora_params.items():
        for t, factors in layer.items():
            flat[f'{layer_name}/{t}/a'] = np.asarray(factors['a'],
                                                     np.float32)
            flat[f'{layer_name}/{t}/b'] = np.asarray(factors['b'],
                                                     np.float32)
    np.savez(os.path.join(out_dir, WEIGHTS_FILE), **flat)
    config = {
        'format': 'skypilot-tpu-lora-v1',
        'base_model': base_model,
        'rank': spec.rank,
        'alpha': spec.alpha,
        'targets': list(spec.targets),
        'num_layers': len(lora_params),
    }
    if step is not None:
        config['step'] = int(step)
    # Atomic-ish: weights land before the config that announces them
    # (a scanner never sees a config without loadable weights).
    with open(os.path.join(out_dir, CONFIG_FILE), 'w',
              encoding='utf-8') as f:
        json.dump(config, f, indent=2)
    return out_dir


def load_adapter(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(config, per-layer factors) from an artifact directory."""
    with open(os.path.join(path, CONFIG_FILE), encoding='utf-8') as f:
        config = json.load(f)
    params: Dict[str, Any] = {}
    with np.load(os.path.join(path, WEIGHTS_FILE)) as z:
        for key in z.files:
            layer_name, t, which = key.split('/')
            params.setdefault(layer_name, {}).setdefault(t, {})[which] \
                = z[key]
    return config, params


def load_spec(config: Dict[str, Any]) -> LoraSpec:
    return LoraSpec(rank=int(config['rank']),
                    alpha=float(config['alpha']),
                    targets=tuple(config['targets']))


def list_adapter_dirs(adapter_dir: str) -> List[str]:
    """Subdirectories of `adapter_dir` that hold an adapter artifact
    (name = directory basename)."""
    if not os.path.isdir(adapter_dir):
        return []
    out = []
    for name in sorted(os.listdir(adapter_dir)):
        if os.path.isfile(os.path.join(adapter_dir, name, CONFIG_FILE)):
            out.append(name)
    return out
