"""Mixtral-family sparse-MoE decoder with expert parallelism.

Recipe model #3 (BASELINE.md config 5: Mixtral 8x7B expert-parallel on
v5p-128). Llama backbone (RMSNorm/RoPE/GQA) with a top-k routed MoE
FFN. Experts live in stacked weights with a leading `expert` logical
axis → sharded over the mesh's `expert` axis; token dispatch/combine
are capacity-bounded einsums (the TPU-native MoE formulation — XLA
lowers the sharded einsums to all-to-alls over ICI), not per-expert
Python loops.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama as llama_lib

Dtype = Any


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    max_seq_len: int = 8192
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    embed_dim: int = 4096
    mlp_dim: int = 14336
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_loss_weight: float = 0.02
    rope_theta: float = 1_000_000.0
    rope_scaling: Optional[llama_lib.RopeScaling] = None
    norm_eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    # LM-head logits precision; None = f32 (see llama.LlamaConfig).
    logits_dtype: Optional[Dtype] = None
    remat: bool = False
    # Paged KV cache for serving (see llama.LlamaConfig).
    kv_page_size: int = 16
    kv_total_pages: int = 128

    @classmethod
    def mixtral_8x7b(cls, **kw) -> 'MixtralConfig':
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> 'MixtralConfig':
        return cls(vocab_size=512, max_seq_len=256, num_layers=2,
                   num_heads=4, num_kv_heads=2, embed_dim=128, mlp_dim=256,
                   num_experts=4, experts_per_token=2, **kw)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    def as_llama(self) -> llama_lib.LlamaConfig:
        return llama_lib.LlamaConfig(
            vocab_size=self.vocab_size, max_seq_len=self.max_seq_len,
            num_layers=self.num_layers, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, embed_dim=self.embed_dim,
            mlp_dim=self.mlp_dim, rope_theta=self.rope_theta,
            rope_scaling=self.rope_scaling,
            norm_eps=self.norm_eps, dtype=self.dtype, remat=self.remat,
            kv_page_size=self.kv_page_size,
            kv_total_pages=self.kv_total_pages)


class MoEFeedForward(nn.Module):
    """Top-k routed SwiGLU experts via capacity-bounded dispatch."""
    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        batch, seq, dim = x.shape
        num_exp, top_k = cfg.num_experts, cfg.experts_per_token

        router = nn.Dense(
            num_exp, use_bias=False, dtype=jnp.float32, name='router',
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('embed', 'expert')))
        gate_logits = router(x.astype(jnp.float32))          # [B,S,E]
        gate_probs = jax.nn.softmax(gate_logits, axis=-1)

        # Top-k routing weights, renormalized over the chosen experts.
        top_w, top_idx = jax.lax.top_k(gate_probs, top_k)    # [B,S,K]
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)

        # Capacity per expert (tokens an expert processes per batch row).
        capacity = int(cfg.capacity_factor * seq * top_k / num_exp)
        capacity = max(capacity, top_k)

        # Build dispatch/combine tensors [B,S,E,C].
        expert_onehot = jax.nn.one_hot(top_idx, num_exp,
                                       dtype=jnp.float32)   # [B,S,K,E]
        # Position of each (token, k) within its expert's queue:
        # cumulative count of prior assignments to the same expert.
        flat = expert_onehot.reshape(batch, seq * top_k, num_exp)
        positions = jnp.cumsum(flat, axis=1) - flat          # [B,S*K,E]
        positions = positions.reshape(batch, seq, top_k, num_exp)
        within_capacity = positions < capacity
        pos_onehot = jax.nn.one_hot(
            jnp.sum(positions * expert_onehot, axis=-1).astype(jnp.int32),
            capacity, dtype=jnp.float32)                     # [B,S,K,C]
        dispatch = jnp.einsum(
            'bske,bskc->bsec',
            expert_onehot * within_capacity.astype(jnp.float32),
            pos_onehot)                                      # [B,S,E,C]
        combine = jnp.einsum('bsk,bske,bskc->bsec',
                             top_w,
                             expert_onehot *
                             within_capacity.astype(jnp.float32),
                             pos_onehot)

        dispatch = nn.with_logical_constraint(
            dispatch, ('batch', 'seq', 'expert', None))
        # Route tokens to experts: [E,B,C,D] — expert-major layout puts
        # the all-to-all on the expert axis.
        expert_in = jnp.einsum('bsec,bsd->ebcd', dispatch,
                               x.astype(jnp.float32)).astype(cfg.dtype)
        expert_in = nn.with_logical_constraint(
            expert_in, ('expert', 'batch', None, 'act_embed'))

        def stacked(name: str, shape, axes):
            return self.param(
                name,
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02), axes),
                shape, jnp.float32).astype(cfg.dtype)

        w_gate = stacked('w_gate', (num_exp, dim, cfg.mlp_dim),
                         ('expert', 'embed', 'mlp'))
        w_up = stacked('w_up', (num_exp, dim, cfg.mlp_dim),
                       ('expert', 'embed', 'mlp'))
        w_down = stacked('w_down', (num_exp, cfg.mlp_dim, dim),
                         ('expert', 'mlp', 'embed'))

        h = nn.silu(jnp.einsum('ebcd,edf->ebcf', expert_in, w_gate)) * \
            jnp.einsum('ebcd,edf->ebcf', expert_in, w_up)
        h = nn.with_logical_constraint(h, ('expert', 'batch', None, 'mlp'))
        expert_out = jnp.einsum('ebcf,efd->ebcd', h, w_down)

        out = jnp.einsum('bsec,ebcd->bsd',
                         combine, expert_out.astype(jnp.float32))
        out = out.astype(cfg.dtype)

        # Load-balancing auxiliary loss (Switch-style): mean prob x
        # mean assignment fraction per expert.
        assign_frac = jnp.mean(
            jnp.sum(expert_onehot, axis=2), axis=(0, 1))     # [E]
        prob_frac = jnp.mean(gate_probs, axis=(0, 1))        # [E]
        aux_loss = num_exp * jnp.sum(assign_frac * prob_frac) / top_k
        return out, aux_loss


class Block(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False
                 ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        lcfg = cfg.as_llama()
        x = x + llama_lib.Attention(lcfg, name='attn')(
            llama_lib.RMSNorm(cfg.norm_eps, cfg.dtype, name='attn_norm')(x),
            positions, decode=decode, page_indices=page_indices,
            prefill=prefill)
        moe_out, aux = MoEFeedForward(cfg, name='moe')(
            llama_lib.RMSNorm(cfg.norm_eps, cfg.dtype, name='moe_norm')(x))
        x = x + moe_out
        return nn.with_logical_constraint(
            x, ('batch', 'seq', 'act_embed')), aux


class Mixtral(nn.Module):
    """Returns (logits [B,S,V] f32, aux_loss scalar)."""
    config: MixtralConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 return_hidden: bool = False):
        """Training: (logits, aux_loss). decode=True (serving): logits
        only — the KV-cache path of the shared llama attention, so the
        generate/continuous-batching engines drive Mixtral unchanged.
        `return_hidden=True` swaps logits for the post-final_norm
        hidden states (the fused-loss path, ops/fused_xent.py)."""
        cfg = self.config
        batch, seq = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
        embed = self.param(
            'tok_embed',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('vocab', 'table_embed')),
            (cfg.vocab_size, cfg.embed_dim), jnp.float32)
        x = embed.astype(cfg.dtype)[tokens]
        x = nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))

        block = Block
        if cfg.remat:
            assert not decode, 'remat is a training-path option'
            block = nn.remat(Block, prevent_cse=False)
        total_aux = jnp.zeros((), jnp.float32)
        for i in range(cfg.num_layers):
            x, aux = block(cfg, name=f'layer_{i}')(x, positions,
                                                   decode=decode,
                                                   page_indices=page_indices,
                                                   prefill=prefill)
            total_aux = total_aux + aux
        x = llama_lib.RMSNorm(cfg.norm_eps, cfg.dtype, name='final_norm')(x)
        head = self.param(
            'lm_head',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('embed', 'vocab')),
            (cfg.embed_dim, cfg.vocab_size), jnp.float32)
        if return_hidden:
            hidden = nn.with_logical_constraint(
                x, ('batch', 'seq', 'act_embed'))
            if decode:
                return hidden
            aux_loss = (cfg.router_aux_loss_weight * total_aux /
                        cfg.num_layers)
            return hidden, aux_loss
        # bf16 operands; accumulation dtype from cfg.logits_dtype
        # (None = f32 — same knob as the other families).
        logits = jnp.einsum('bse,ev->bsv', x.astype(cfg.dtype),
                            head.astype(cfg.dtype),
                            preferred_element_type=(cfg.logits_dtype or
                                                    jnp.float32))
        logits = nn.with_logical_constraint(logits,
                                            ('batch', 'seq', 'vocab'))
        if decode:
            return logits  # aux loss is a training-only signal
        aux_loss = cfg.router_aux_loss_weight * total_aux / cfg.num_layers
        return logits, aux_loss


def moe_next_token_loss(outputs, tokens: jax.Array) -> jax.Array:
    """Loss fn for ShardedTrainer: CE + router aux loss."""
    from skypilot_tpu.parallel.train import next_token_loss
    logits, aux_loss = outputs
    return next_token_loss(logits, tokens) + aux_loss


# The fused blockwise-xent trainer path handles the (hidden, aux)
# tuple generically — flag this loss as fused-compatible so
# ShardedTrainer's auto-detection keeps Mixtral on the fast path.
moe_next_token_loss.fused_ok = True
