"""DeepSeek-V2-family decoder: Multi-head Latent Attention (MLA).

Recipe model #4. MLA compresses the KV cache into a per-token latent
(`kv_lora_rank` dims) plus a small shared rotary key (`rope_head_dim`
dims) — e.g. 576 cached dims/token where Llama-3-8B caches 2048 —
so serving batch sizes scale ~8x further in the same HBM. The decode
path uses the ABSORBED formulation (score = (W_uk^T q)·c, output =
W_uv (Σ p·c)): attention runs directly against the latent cache and
the per-head K/V are never materialized at decode time, which is
exactly the MXU-friendly shape — two extra small matmuls instead of
an 8x-larger HBM-bound cache scan.

The reference orchestrator ships DeepSeek only as a user recipe
(`llm/deepseek-r1/`); here the family is a first-class model with the
same logical-axis sharding scheme as models/{gpt,llama,mixtral}.py.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models.llama import (FeedForward as SwiGLU, RMSNorm,
                                       apply_rope, _proj)
from skypilot_tpu.ops import attention as attention_ops

Dtype = Any


@dataclasses.dataclass(frozen=True)
class DeepseekConfig:
    vocab_size: int = 102400
    max_seq_len: int = 4096
    num_layers: int = 27
    num_heads: int = 16
    embed_dim: int = 2048
    mlp_dim: int = 10944
    # MLA dims (DeepSeek-V2-Lite defaults): latent cache rank, the
    # decoupled rotary dims, and the no-position ("nope") head dims.
    kv_lora_rank: int = 512
    q_lora_rank: int = 0        # 0 = full-rank queries (V2-Lite)
    rope_head_dim: int = 64
    nope_head_dim: int = 128
    v_head_dim: int = 128
    rope_theta: float = 10_000.0
    norm_eps: float = 1e-6
    dtype: Dtype = jnp.bfloat16
    # LM-head logits precision; None = f32 (see llama.LlamaConfig).
    logits_dtype: Optional[Dtype] = None
    remat: bool = False

    @classmethod
    def v2_lite(cls, **kw) -> 'DeepseekConfig':
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> 'DeepseekConfig':
        return cls(vocab_size=512, max_seq_len=256, num_layers=2,
                   num_heads=4, embed_dim=128, mlp_dim=384,
                   kv_lora_rank=32, q_lora_rank=0, rope_head_dim=16,
                   nope_head_dim=32, v_head_dim=32, **kw)

    @property
    def qk_head_dim(self) -> int:
        return self.nope_head_dim + self.rope_head_dim


class MLAttention(nn.Module):
    """Multi-head latent attention with an absorbed decode path.

    Cache contract (decode=True): per-token latents only —
    'latent_cache' [B, T, kv_lora_rank] + 'rope_cache'
    [B, T, rope_head_dim] — written at per-row `positions`, the same
    positions semantics as the other families so `models/generate.py`
    and the batching engine drive this model unchanged.
    """
    config: DeepseekConfig

    def _queries(self, x: jax.Array):
        """[B,S,H,d_nope], [B,S,H,d_rope] (rope not yet applied)."""
        cfg = self.config
        batch, seq, _ = x.shape
        if cfg.q_lora_rank:
            q = _proj(cfg.q_lora_rank, ('embed', 'kv'), cfg.dtype,
                      'wq_a')(x)
            q = RMSNorm(cfg.norm_eps, cfg.dtype, name='q_norm')(q)
            q = _proj(cfg.num_heads * cfg.qk_head_dim, ('kv', 'heads'),
                      cfg.dtype, 'wq_b')(q)
        else:
            q = _proj(cfg.num_heads * cfg.qk_head_dim, ('embed', 'heads'),
                      cfg.dtype, 'wq')(x)
        q = q.reshape(batch, seq, cfg.num_heads, cfg.qk_head_dim)
        return (q[..., :cfg.nope_head_dim],
                q[..., cfg.nope_head_dim:])

    def _latents(self, x: jax.Array, positions: jax.Array):
        """Compressed per-token cache entries: c_kv [B,S,d_c] (normed)
        and the shared rotary key k_rope [B,S,d_rope] (rope applied)."""
        cfg = self.config
        kv = _proj(cfg.kv_lora_rank + cfg.rope_head_dim, ('embed', 'kv'),
                   cfg.dtype, 'wkv_a')(x)
        c_kv = RMSNorm(cfg.norm_eps, cfg.dtype, name='kv_norm')(
            kv[..., :cfg.kv_lora_rank])
        k_rope = kv[..., None, cfg.kv_lora_rank:]          # [B,S,1,d_r]
        k_rope = apply_rope(k_rope, positions, cfg.rope_theta)[:, :, 0]
        return c_kv, k_rope

    def _wkv_b(self) -> jax.Array:
        """[d_c, H, d_nope + d_v] decompression weight (split into
        W_uk / W_uv by the callers)."""
        cfg = self.config
        return self.param(
            'wkv_b',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                ('kv', 'heads', None)),
            (cfg.kv_lora_rank, cfg.num_heads,
             cfg.nope_head_dim + cfg.v_head_dim), jnp.float32)

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False) -> jax.Array:
        assert page_indices is None, (
            'MLA caches latents, not K/V pages; paged serving of the '
            'deepseek family uses the dense latent cache (it is already '
            '~8x smaller than paged full K/V).')
        cfg = self.config
        batch, seq, _ = x.shape
        q_nope, q_rope = self._queries(x)
        q_rope = apply_rope(q_rope, positions, cfg.rope_theta)
        c_kv, k_rope = self._latents(x, positions)
        wkv_b = self._wkv_b().astype(cfg.dtype)
        w_uk = wkv_b[..., :cfg.nope_head_dim]       # [d_c, H, d_n]
        w_uv = wkv_b[..., cfg.nope_head_dim:]       # [d_c, H, d_v]

        if decode:
            # ABSORBED attention against the latent cache, for any
            # chunk size: S=1 incremental decode, S=P chunked prefill,
            # S=k+1 speculative verification. The chunk's latents are
            # written at per-row offsets BEFORE attending, so stale
            # entries from rejected drafts are always overwritten
            # first (same contract as ops.chunked_cache_attention).
            latent = self.variable(
                'cache', 'latent_cache', jnp.zeros,
                (batch, cfg.max_seq_len, cfg.kv_lora_rank), cfg.dtype)
            ropes = self.variable(
                'cache', 'rope_cache', jnp.zeros,
                (batch, cfg.max_seq_len, cfg.rope_head_dim), cfg.dtype)
            start = positions[:, 0]                              # [B]

            def write_rows(cache_row, new_rows, p):
                return jax.lax.dynamic_update_slice(
                    cache_row, new_rows, (p, 0))

            latent.value = jax.vmap(write_rows)(
                latent.value, c_kv.astype(cfg.dtype), start)
            ropes.value = jax.vmap(write_rows)(
                ropes.value, k_rope.astype(cfg.dtype), start)
            # q absorbed into latent space: [B,S,H,d_c]
            q_eff = jnp.einsum('bshn,chn->bshc',
                               q_nope.astype(jnp.float32),
                               w_uk.astype(jnp.float32))
            if prefill:
                # PREFILL fast path (static; empty-cache contract):
                # attend only within the chunk — S x S instead of
                # S x max_seq_len f32 scores.
                k_lat = c_kv.astype(jnp.float32)
                k_rop = k_rope.astype(jnp.float32)
                mask = (jnp.arange(seq)[None, :]
                        <= jnp.arange(seq)[:, None])[None, None]
            else:
                k_lat = latent.value.astype(jnp.float32)
                k_rop = ropes.value.astype(jnp.float32)
                mask = (jnp.arange(cfg.max_seq_len)[None, None, :]
                        <= positions[:, :, None])[:, None]  # [B,1,S,T]
            scores = (
                jnp.einsum('bshc,btc->bhst', q_eff, k_lat) +
                jnp.einsum('bshr,btr->bhst',
                           q_rope.astype(jnp.float32), k_rop)
            ) / jnp.sqrt(float(cfg.qk_head_dim))
            scores = jnp.where(mask, scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1)
            # Context in latent space, decompressed once per head.
            ctx_lat = jnp.einsum('bhst,btc->bshc', probs, k_lat)
            out = jnp.einsum('bshc,chv->bshv', ctx_lat,
                             w_uv.astype(jnp.float32))
            out = out.astype(cfg.dtype)              # [B,S,H,d_v]
        else:
            # Training: decompress K and V from the chunk's latents
            # (no cache) and run standard causal attention at
            # qk_head_dim.
            k_nope = jnp.einsum('btc,chn->bthn', c_kv, w_uk)
            v = jnp.einsum('btc,chv->bthv', c_kv, w_uv)
            k = jnp.concatenate([
                k_nope,
                jnp.broadcast_to(k_rope[:, :, None],
                                 (batch, seq, cfg.num_heads,
                                  cfg.rope_head_dim))], axis=-1)
            q = jnp.concatenate([q_nope, q_rope], axis=-1)
            q = nn.with_logical_constraint(q,
                                           ('batch', 'seq', 'heads', 'kv'))
            k = nn.with_logical_constraint(k,
                                           ('batch', 'seq', 'heads', 'kv'))
            v = nn.with_logical_constraint(v,
                                           ('batch', 'seq', 'heads', 'kv'))
            out = attention_ops.dot_product_attention(q, k, v, causal=True)
        out = out.reshape(batch, seq, cfg.num_heads * cfg.v_head_dim)
        return _proj(cfg.embed_dim, ('heads', 'embed'), cfg.dtype,
                     'wo')(out)


class Block(nn.Module):
    config: DeepseekConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False) -> jax.Array:
        cfg = self.config
        x = x + MLAttention(cfg, name='attn')(
            RMSNorm(cfg.norm_eps, cfg.dtype, name='attn_norm')(x),
            positions, decode, page_indices, prefill)
        # llama's SwiGLU block is duck-typed on mlp_dim/embed_dim/dtype
        # (same reuse as mixtral.py).
        x = x + SwiGLU(cfg, name='mlp')(
            RMSNorm(cfg.norm_eps, cfg.dtype, name='mlp_norm')(x))
        return nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))


class Deepseek(nn.Module):
    """DeepSeek decoder; __call__ returns logits [B, S, vocab].

    `return_hidden=True` returns the post-final_norm hidden states
    (the fused blockwise-loss path, ops/fused_xent.py — at DeepSeek's
    102k vocab the skipped [B, S, V] logits dominate training HBM).
    """
    config: DeepseekConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        batch, seq = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
        embed = self.param(
            'tok_embed',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                ('vocab', 'table_embed')),
            (cfg.vocab_size, cfg.embed_dim), jnp.float32)
        x = embed.astype(cfg.dtype)[tokens]
        x = nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))

        block = Block
        if cfg.remat:
            block = nn.remat(Block, prevent_cse=False,
                             static_argnums=(3, 5))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f'layer_{i}')(x, positions, decode,
                                              page_indices, prefill)
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name='final_norm')(x)
        head = self.param(
            'lm_head',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('embed', 'vocab')),
            (cfg.embed_dim, cfg.vocab_size), jnp.float32)
        if return_hidden:
            return nn.with_logical_constraint(
                x, ('batch', 'seq', 'act_embed'))
        logits = jnp.einsum('bse,ev->bsv', x.astype(cfg.dtype),
                            head.astype(cfg.dtype),
                            preferred_element_type=(cfg.logits_dtype or
                                                    jnp.float32))
        return nn.with_logical_constraint(logits, ('batch', 'seq', 'vocab'))
