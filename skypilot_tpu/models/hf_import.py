"""HuggingFace checkpoint import: safetensors -> flax params.

Loads a locally-downloaded HF repo directory (e.g. the target of an
`hf://` Storage COPY, `data/storage.py`) and produces (model, params)
for the in-framework model families, so the serving stack
(`recipes/serve_lm.py --hf`, continuous batching, speculative
decoding) and the finetune recipes (`recipes/train_lm.py
--init-from-hf`) run REAL checkpoints — the gap the reference fills
with its `llm/` recipe set (reference: `llm/llama-3_1-finetuning/`,
`llm/mixtral/`, `llm/deepseek-r1/` serve real weights; here the
conversion is in-framework).

Supported `model_type`s (config.json): `llama`, `qwen2` (Qwen2/2.5 —
the llama backbone + q/k/v biases + tied embeddings), `mistral`,
`gpt2`, `mixtral`, `deepseek_v2` (dense-MLP checkpoints; MoE-layer
DeepSeek V2 rejects with a clear error). Weights are read from
*.safetensors (sharded via model.safetensors.index.json) or
pytorch_model.bin, converted to f32 numpy (our params are f32
masters; compute casts to bf16).

Convention notes (verified by logit-parity tests against the
torch/transformers implementations, tests/unit_tests/test_hf_import.py):
- llama/mixtral/gpt2 rope + head layouts match ours directly: HF
  stores q/k projections pre-permuted for the half-split rotate_half
  convention, which is what ops-level `apply_rope` implements.
- deepseek_v2 applies INTERLEAVED rope (complex pairs (x_{2i},
  x_{2i+1})); our `apply_rope` is half-split ((x_i, x_{i+d/2})). The
  rope rows of `kv_a_proj_with_mqa` and of each head of the q
  projection are permuted even-then-odd at conversion time so the
  numerics match exactly.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class HfImportError(Exception):
    """Unsupported or malformed HF checkpoint."""


def read_config(model_dir: str) -> Dict[str, Any]:
    path = os.path.join(model_dir, 'config.json')
    if not os.path.exists(path):
        raise HfImportError(f'no config.json under {model_dir!r} — '
                            'is this a downloaded HF model repo?')
    with open(path, 'r', encoding='utf-8') as f:
        return json.load(f)


def load_state_dict(model_dir: str) -> Dict[str, np.ndarray]:
    """All tensors as f32 numpy, from safetensors (single or sharded
    via the index file) or a pytorch_model.bin fallback."""
    index = os.path.join(model_dir, 'model.safetensors.index.json')
    single = os.path.join(model_dir, 'model.safetensors')
    out: Dict[str, np.ndarray] = {}
    if os.path.exists(index):
        with open(index, 'r', encoding='utf-8') as f:
            weight_map = json.load(f)['weight_map']
        for shard in sorted(set(weight_map.values())):
            out.update(_load_safetensors(os.path.join(model_dir, shard)))
        return out
    if os.path.exists(single):
        return _load_safetensors(single)
    torch_bin = os.path.join(model_dir, 'pytorch_model.bin')
    if os.path.exists(torch_bin):
        import torch
        sd = torch.load(torch_bin, map_location='cpu',
                        weights_only=True)
        return {k: v.to(torch.float32).numpy() for k, v in sd.items()}
    raise HfImportError(
        f'no model.safetensors[.index.json] or pytorch_model.bin '
        f'under {model_dir!r}')


def _load_safetensors(path: str) -> Dict[str, np.ndarray]:
    # safetensors.numpy cannot represent bf16; go through torch (cpu).
    from safetensors import torch as st_torch
    import torch
    return {k: v.to(torch.float32).numpy()
            for k, v in st_torch.load_file(path).items()}


def _deinterleave_rope_rows(w: np.ndarray, rope_dim: int) -> np.ndarray:
    """Permute the LAST `rope_dim` output rows of a [out, in] weight
    from interleaved pairs ((x0,x1),(x2,x3),...) to the half-split
    layout ((x0,x2,...),(x1,x3,...)) our `apply_rope` expects."""
    head, rope = w[:-rope_dim], w[-rope_dim:]
    perm = np.concatenate([np.arange(0, rope_dim, 2),
                           np.arange(1, rope_dim, 2)])
    return np.concatenate([head, rope[perm]], axis=0)


def _t(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


def _parse_rope_scaling(cfg_json: Dict[str, Any]):
    """config.json `rope_scaling` -> models.llama.RopeScaling (or None).

    Llama 3.1/3.2 ship `{'rope_type': 'llama3', ...}` (older exports
    use the key `type`); importing those without rescaling inv_freq
    would silently corrupt logits at every position, so unsupported
    schemes (yarn, dynamic, longrope) raise instead of being ignored.
    """
    rs = cfg_json.get('rope_scaling')
    if rs is None:
        return None
    rope_type = rs.get('rope_type') or rs.get('type')
    if rope_type in (None, 'default'):
        return None
    from skypilot_tpu.models.llama import RopeScaling
    try:
        if rope_type == 'llama3':
            return RopeScaling(
                rope_type='llama3',
                factor=float(rs['factor']),
                low_freq_factor=float(rs.get('low_freq_factor', 1.0)),
                high_freq_factor=float(rs.get('high_freq_factor', 4.0)),
                original_max_position_embeddings=int(
                    rs['original_max_position_embeddings']))
        if rope_type == 'linear':
            return RopeScaling(rope_type='linear',
                               factor=float(rs['factor']))
    except KeyError as e:
        raise HfImportError(
            f'rope_scaling block is missing required key {e} for '
            f'rope_type {rope_type!r}: {rs!r}') from e
    raise HfImportError(
        f'rope_scaling type {rope_type!r} is not supported (supported: '
        f'llama3, linear) — importing this checkpoint without its '
        f'frequency rescaling would produce silently wrong logits.')


# ---------------------------------------------------------------------------
# Per-family conversion. Each returns (flax module, params pytree).


def _convert_llama_like(cfg_json: Dict[str, Any],
                        sd: Dict[str, np.ndarray],
                        max_seq_len: Optional[int],
                        moe: bool, **config_overrides):
    """Shared body for llama and mixtral (same backbone)."""
    num_layers = cfg_json['num_hidden_layers']
    common = dict(
        vocab_size=cfg_json['vocab_size'],
        max_seq_len=max_seq_len or cfg_json['max_position_embeddings'],
        num_layers=num_layers,
        num_heads=cfg_json['num_attention_heads'],
        num_kv_heads=cfg_json.get('num_key_value_heads',
                                  cfg_json['num_attention_heads']),
        embed_dim=cfg_json['hidden_size'],
        mlp_dim=cfg_json['intermediate_size'],
        rope_theta=float(cfg_json.get('rope_theta', 10000.0)),
        rope_scaling=_parse_rope_scaling(cfg_json),
        norm_eps=float(cfg_json.get('rms_norm_eps', 1e-5)),
    )
    common.update(config_overrides)
    params: Dict[str, Any] = {
        'tok_embed': sd['model.embed_tokens.weight'],
        'final_norm': {'scale': sd['model.norm.weight']},
    }
    if cfg_json.get('tie_word_embeddings'):
        params['lm_head'] = _t(sd['model.embed_tokens.weight'])
    else:
        params['lm_head'] = _t(sd['lm_head.weight'])
    # Qwen2-family variant: q/k/v carry biases (detected from the
    # checkpoint, so 'qwen2' and biased llama-likes both work).
    qkv_bias = 'model.layers.0.self_attn.q_proj.bias' in sd
    if qkv_bias and not moe:
        common['qkv_bias'] = True
    for i in range(num_layers):
        p = f'model.layers.{i}.'
        layer: Dict[str, Any] = {
            'attn': {
                'wq': {'kernel': _t(sd[p + 'self_attn.q_proj.weight'])},
                'wk': {'kernel': _t(sd[p + 'self_attn.k_proj.weight'])},
                'wv': {'kernel': _t(sd[p + 'self_attn.v_proj.weight'])},
                'wo': {'kernel': _t(sd[p + 'self_attn.o_proj.weight'])},
            },
            'attn_norm': {'scale': sd[p + 'input_layernorm.weight']},
        }
        if qkv_bias and not moe:
            for w, hf in (('wq', 'q_proj'), ('wk', 'k_proj'),
                          ('wv', 'v_proj')):
                layer['attn'][w]['bias'] = \
                    sd[p + f'self_attn.{hf}.bias']
        post_norm = sd[p + 'post_attention_layernorm.weight']
        if moe:
            n_exp = cfg_json['num_local_experts']
            ep = p + 'block_sparse_moe.'
            layer['moe'] = {
                'router': {'kernel': _t(sd[ep + 'gate.weight'])},
                # HF per-expert Linears -> stacked [E, in, out]: w1 =
                # gate, w3 = up (both [F, D]), w2 = down ([D, F]).
                'w_gate': np.stack([
                    _t(sd[f'{ep}experts.{j}.w1.weight'])
                    for j in range(n_exp)]),
                'w_up': np.stack([
                    _t(sd[f'{ep}experts.{j}.w3.weight'])
                    for j in range(n_exp)]),
                'w_down': np.stack([
                    _t(sd[f'{ep}experts.{j}.w2.weight'])
                    for j in range(n_exp)]),
            }
            layer['moe_norm'] = {'scale': post_norm}
        else:
            layer['mlp'] = {
                'w_gate': {'kernel': _t(sd[p + 'mlp.gate_proj.weight'])},
                'w_up': {'kernel': _t(sd[p + 'mlp.up_proj.weight'])},
                'w_down': {'kernel': _t(sd[p + 'mlp.down_proj.weight'])},
            }
            layer['mlp_norm'] = {'scale': post_norm}
        params[f'layer_{i}'] = layer
    if moe:
        from skypilot_tpu.models.mixtral import Mixtral, MixtralConfig
        # Inference default: capacity_factor = E/K makes per-expert
        # capacity = seq — the worst case (every token routing its K
        # distinct experts to one queue) — so NO routed tokens are
        # dropped and outputs match the checkpoint's reference
        # implementation exactly (the training default of 1.25
        # silently drops prefill tokens). Finetuning can pass a
        # tighter capacity_factor override explicitly.
        common.setdefault('capacity_factor',
                          float(cfg_json['num_local_experts']) /
                          float(cfg_json['num_experts_per_tok']))
        cfg = MixtralConfig(
            num_experts=cfg_json['num_local_experts'],
            experts_per_token=cfg_json['num_experts_per_tok'],
            **common)
        return Mixtral(cfg), params
    from skypilot_tpu.models.llama import Llama, LlamaConfig
    return Llama(LlamaConfig(**common)), params


def _convert_llama(cfg_json, sd, max_seq_len, **overrides):
    return _convert_llama_like(cfg_json, sd, max_seq_len, moe=False,
                               **overrides)


def _convert_mixtral(cfg_json, sd, max_seq_len, **overrides):
    return _convert_llama_like(cfg_json, sd, max_seq_len, moe=True,
                               **overrides)


def _convert_gpt2(cfg_json, sd, max_seq_len, **overrides):
    from skypilot_tpu.models.gpt import GPT, GPTConfig
    num_layers = cfg_json['n_layer']
    cfg = GPTConfig(
        vocab_size=cfg_json['vocab_size'],
        block_size=max_seq_len or cfg_json['n_positions'],
        num_layers=num_layers,
        num_heads=cfg_json['n_head'],
        embed_dim=cfg_json['n_embd'],
        norm_eps=float(cfg_json.get('layer_norm_epsilon', 1e-5)),
        **overrides)

    def g(key: str) -> np.ndarray:
        # Some exports keep the 'transformer.' prefix, some drop it.
        val = sd.get('transformer.' + key, sd.get(key))
        if val is None:
            raise HfImportError(
                f'checkpoint is missing tensor {key!r} (tried '
                f'"transformer.{key}" and "{key}")')
        return val

    params: Dict[str, Any] = {
        'wte': g('wte.weight'),
        'wpe': g('wpe.weight')[:cfg.block_size],
        'ln_f': {'scale': g('ln_f.weight'), 'bias': g('ln_f.bias')},
    }
    for i in range(num_layers):
        p = f'h.{i}.'
        # HF GPT-2 uses Conv1D ([in, out] weights) — no transpose.
        params[f'h_{i}'] = {
            'ln_1': {'scale': g(p + 'ln_1.weight'),
                     'bias': g(p + 'ln_1.bias')},
            'ln_2': {'scale': g(p + 'ln_2.weight'),
                     'bias': g(p + 'ln_2.bias')},
            'attn': {
                'c_attn': {'kernel': g(p + 'attn.c_attn.weight'),
                           'bias': g(p + 'attn.c_attn.bias')},
                'c_proj': {'kernel': g(p + 'attn.c_proj.weight'),
                           'bias': g(p + 'attn.c_proj.bias')},
            },
            'mlp': {
                'c_fc': {'kernel': g(p + 'mlp.c_fc.weight'),
                         'bias': g(p + 'mlp.c_fc.bias')},
                'c_proj': {'kernel': g(p + 'mlp.c_proj.weight'),
                           'bias': g(p + 'mlp.c_proj.bias')},
            },
        }
    return GPT(cfg), params


def _convert_deepseek(cfg_json, sd, max_seq_len, **overrides):
    from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
    if _parse_rope_scaling(cfg_json) is not None:
        # Real DeepSeek V2 long-context checkpoints ship yarn scaling
        # (rejected in _parse_rope_scaling); llama3/linear scaling is
        # not wired into the MLA rope path either — refuse rather than
        # import with silently wrong positional frequencies.
        raise HfImportError(
            'rope_scaling is not supported for deepseek_v2 imports yet')
    # (MoE DeepSeek V2 is rejected in load_hf_checkpoint, before the
    # state dict is read.)
    num_layers = cfg_json['num_hidden_layers']
    rope_dim = cfg_json['qk_rope_head_dim']
    nope_dim = cfg_json['qk_nope_head_dim']
    num_heads = cfg_json['num_attention_heads']
    q_lora = cfg_json.get('q_lora_rank') or 0
    cfg = DeepseekConfig(
        vocab_size=cfg_json['vocab_size'],
        max_seq_len=max_seq_len or cfg_json['max_position_embeddings'],
        num_layers=num_layers,
        num_heads=num_heads,
        embed_dim=cfg_json['hidden_size'],
        mlp_dim=cfg_json['intermediate_size'],
        kv_lora_rank=cfg_json['kv_lora_rank'],
        q_lora_rank=q_lora,
        rope_head_dim=rope_dim,
        nope_head_dim=nope_dim,
        v_head_dim=cfg_json['v_head_dim'],
        rope_theta=float(cfg_json.get('rope_theta', 10000.0)),
        norm_eps=float(cfg_json.get('rms_norm_eps', 1e-6)),
        **overrides)

    def fix_q(w: np.ndarray) -> np.ndarray:
        """De-interleave the rope rows of EACH HEAD of a q projection
        ([H * (nope+rope), in])."""
        w = w.reshape(num_heads, nope_dim + rope_dim, -1)
        w = np.stack([_deinterleave_rope_rows(h, rope_dim) for h in w])
        return w.reshape(num_heads * (nope_dim + rope_dim), -1)

    params: Dict[str, Any] = {
        'tok_embed': sd['model.embed_tokens.weight'],
        'final_norm': {'scale': sd['model.norm.weight']},
    }
    if cfg_json.get('tie_word_embeddings'):
        params['lm_head'] = _t(sd['model.embed_tokens.weight'])
    else:
        params['lm_head'] = _t(sd['lm_head.weight'])
    for i in range(num_layers):
        p = f'model.layers.{i}.'
        attn: Dict[str, Any] = {
            # kv_a rope rows live at the END of the output: same
            # de-interleave, on the joint [d_c + d_rope, D] weight.
            'wkv_a': {'kernel': _t(_deinterleave_rope_rows(
                sd[p + 'self_attn.kv_a_proj_with_mqa.weight'],
                rope_dim))},
            'kv_norm': {'scale': sd[p + 'self_attn.kv_a_layernorm.weight']},
            # [H*(nope+v), d_c] -> [d_c, H, nope+v]
            'wkv_b': _t(sd[p + 'self_attn.kv_b_proj.weight']).reshape(
                cfg.kv_lora_rank, num_heads, nope_dim + cfg.v_head_dim),
            'wo': {'kernel': _t(sd[p + 'self_attn.o_proj.weight'])},
        }
        if q_lora:
            attn['wq_a'] = {'kernel': _t(sd[p + 'self_attn.q_a_proj.weight'])}
            attn['q_norm'] = {'scale': sd[p + 'self_attn.q_a_layernorm.weight']}
            attn['wq_b'] = {'kernel': _t(fix_q(
                sd[p + 'self_attn.q_b_proj.weight']))}
        else:
            attn['wq'] = {'kernel': _t(fix_q(
                sd[p + 'self_attn.q_proj.weight']))}
        params[f'layer_{i}'] = {
            'attn': attn,
            'attn_norm': {'scale': sd[p + 'input_layernorm.weight']},
            'mlp': {
                'w_gate': {'kernel': _t(sd[p + 'mlp.gate_proj.weight'])},
                'w_up': {'kernel': _t(sd[p + 'mlp.up_proj.weight'])},
                'w_down': {'kernel': _t(sd[p + 'mlp.down_proj.weight'])},
            },
            'mlp_norm': {'scale': sd[p + 'post_attention_layernorm.weight']},
        }
    return Deepseek(cfg), params


_CONVERTERS: Dict[str, Callable] = {
    'llama': _convert_llama,
    # Qwen2/2.5 = the llama backbone + q/k/v biases (auto-detected
    # from the checkpoint) + usually tied embeddings.
    'qwen2': _convert_llama,
    # Mistral's config is llama-shaped (sliding_window unset/ignored
    # at the context lengths we serve).
    'mistral': _convert_llama,
    'mixtral': _convert_mixtral,
    'gpt2': _convert_gpt2,
    'deepseek_v2': _convert_deepseek,
}


def supported_model_types() -> Tuple[str, ...]:
    return tuple(sorted(_CONVERTERS))


def load_hf_checkpoint(model_dir: str, *,
                       max_seq_len: Optional[int] = None,
                       **config_overrides):
    """(flax module, params) from a local HF model directory.

    `max_seq_len` overrides the checkpoint's max_position_embeddings —
    serving allocates caches of this size per slot, so clamp it to
    what you actually serve (e.g. serve_lm passes its --max-total-len
    budget). `config_overrides` go into the model config (e.g.
    `dtype=jnp.float32` for CPU parity runs, `capacity_factor=...`
    for mixtral routing capacity).
    """
    cfg_json = read_config(model_dir)
    model_type = cfg_json.get('model_type')
    conv = _CONVERTERS.get(model_type)
    if conv is None:
        raise HfImportError(
            f'unsupported model_type {model_type!r}; supported: '
            f'{", ".join(supported_model_types())}')
    trained_ctx = cfg_json.get('n_positions') or cfg_json.get(
        'max_position_embeddings')
    if max_seq_len is not None and trained_ctx \
            and max_seq_len > trained_ctx:
        if model_type == 'gpt2':
            raise HfImportError(
                f'max_seq_len={max_seq_len} exceeds the checkpoint\'s '
                f'trained context (n_positions={trained_ctx}) — GPT-2\'s '
                f'absolute position embeddings cannot extrapolate. '
                f'Serve with --max-total-len <= {trained_ctx}.')
        import warnings
        warnings.warn(
            f'max_seq_len={max_seq_len} exceeds the checkpoint\'s '
            f'trained context ({trained_ctx}): rope positions beyond '
            f'it are untrained extrapolation — expect degraded output '
            f'past {trained_ctx} tokens.', stacklevel=2)
    # Validate rope_scaling BEFORE reading gigabytes of weights
    # (raises for unsupported schemes like yarn/dynamic/longrope).
    _parse_rope_scaling(cfg_json)
    if model_type == 'deepseek_v2' and cfg_json.get('n_routed_experts'):
        # Reject BEFORE reading gigabytes of weights.
        raise HfImportError(
            'DeepSeek V2 checkpoints with routed-expert (MoE) layers '
            'are not supported yet — the in-framework deepseek family '
            'is MLA + dense SwiGLU. Use a dense-MLP export, or the '
            'mixtral family for MoE serving.')
    sd = load_state_dict(model_dir)
    model, params = conv(cfg_json, sd, max_seq_len, **config_overrides)
    _validate_against_init(model, params)
    return model, params


def _validate_against_init(model, params) -> None:
    """Converted tree must match the model's own init tree exactly
    (same leaves, same shapes) — catches mapping drift loudly instead
    of at apply time."""
    import jax
    import jax.numpy as jnp
    import flax.linen as nn
    ref = nn.meta.unbox(jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32)))['params'])
    ref_paths = {tuple(k.key for k in p): leaf.shape for p, leaf in
                 jax.tree_util.tree_flatten_with_path(ref)[0]}
    got_paths = {tuple(k.key for k in p): np.shape(leaf) for p, leaf in
                 jax.tree_util.tree_flatten_with_path(params)[0]}
    missing = sorted(set(ref_paths) - set(got_paths))
    extra = sorted(set(got_paths) - set(ref_paths))
    bad_shape = sorted(
        (k, got_paths[k], ref_paths[k])
        for k in set(ref_paths) & set(got_paths)
        if tuple(got_paths[k]) != tuple(ref_paths[k]))
    if missing or extra or bad_shape:
        raise HfImportError(
            f'converted params do not match the model: '
            f'missing={missing[:5]} extra={extra[:5]} '
            f'shape-mismatches={bad_shape[:5]}')


def load_tokenizer(model_dir: str):
    """transformers AutoTokenizer over the local files (no network)."""
    from transformers import AutoTokenizer
    return AutoTokenizer.from_pretrained(model_dir, local_files_only=True)
