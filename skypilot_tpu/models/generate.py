"""Autoregressive generation with KV cache (serving compute path).

One jitted `lax.scan` drives both prefill and decode: at step t the
input token is the prompt token (teacher-forced) while t < prompt_len,
else the previously sampled token — KV cache carried as flax 'cache'
variables, so per-token cost is O(1) in sequence length. This is the
in-framework inference engine behind `serve` replicas
(`recipes/serve_lm.py`); continuous batching lands in a later round.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def make_generate_fn(model, max_total_len: int,
                     temperature: float = 0.0,
                     eos_id: Optional[int] = None):
    """Returns jitted fn(params, prompt[B,P], rng) -> tokens [B, T].

    Output rows are prompt ++ generated, padded with eos/0 after eos.
    """
    assert max_total_len <= model.config.max_seq_len

    @functools.partial(jax.jit, static_argnums=())
    def generate(params, prompt: jax.Array, rng: jax.Array) -> jax.Array:
        batch, prompt_len = prompt.shape
        cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
            positions=jnp.zeros((batch, 1), jnp.int32), decode=True,
        )['cache']
        import flax.linen as nn
        # init *ran* a step (junk K/V at position 0): reset.
        cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

        def step(carry, t):
            cache, prev_token, rng = carry
            # Input: prompt token while inside the prompt, else sampled.
            in_prompt = t < prompt_len
            tok = jnp.where(
                in_prompt,
                jax.lax.dynamic_index_in_dim(
                    prompt, jnp.minimum(t, prompt_len - 1), axis=1,
                    keepdims=False),
                prev_token)
            positions = jnp.full((batch, 1), t, jnp.int32)
            logits, mutated = model.apply(
                {'params': params, 'cache': cache},
                tok[:, None], positions=positions, decode=True,
                mutable=['cache'])
            logits = logits[:, 0]  # [B, V]
            rng, sub = jax.random.split(rng)
            if temperature > 0:
                sampled = jax.random.categorical(
                    sub, logits / temperature, axis=-1)
            else:
                sampled = jnp.argmax(logits, axis=-1)
            sampled = sampled.astype(jnp.int32)
            return (mutated['cache'], sampled, rng), sampled

        init_token = jnp.zeros((batch,), jnp.int32)
        (_, _, _), sampled_seq = jax.lax.scan(
            step, (cache, init_token, rng),
            jnp.arange(max_total_len - 1))
        sampled_seq = jnp.swapaxes(sampled_seq, 0, 1)  # [B, T-1]

        # Assemble: positions < prompt_len come from the prompt;
        # position p >= prompt_len is the sample from step p-1.
        out = jnp.zeros((batch, max_total_len), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, prompt, (0, 0))
        positions = jnp.arange(max_total_len)[None, :]
        shifted = jnp.pad(sampled_seq, ((0, 0), (1, 0)))  # sample->pos+1
        out = jnp.where(positions >= prompt_len, shifted, out)

        if eos_id is not None:
            hit = jnp.cumsum(
                (out == eos_id) & (positions >= prompt_len), axis=1)
            keep = hit - ((out == eos_id) &
                          (positions >= prompt_len)).astype(hit.dtype) == 0
            out = jnp.where(keep, out, eos_id)
        return out

    return generate


def teacher_forced_logits(model, params, tokens: jax.Array
                          ) -> Tuple[jax.Array, jax.Array]:
    """Decode-mode logits for every position vs full-forward logits.

    Correctness harness: the cached incremental path must match the
    batched forward exactly (tests/unit_tests/test_generate.py).
    """
    batch, seq = tokens.shape
    full = model.apply({'params': params}, tokens)

    cache = model.init(
        jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
        positions=jnp.zeros((batch, 1), jnp.int32), decode=True)['cache']
    import flax.linen as nn
    cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

    def step(cache, t):
        tok = jax.lax.dynamic_slice_in_dim(tokens, t, 1, axis=1)
        positions = jnp.full((batch, 1), t, jnp.int32)
        logits, mutated = model.apply(
            {'params': params, 'cache': cache}, tok,
            positions=positions, decode=True, mutable=['cache'])
        return mutated['cache'], logits[:, 0]

    _, decoded = jax.lax.scan(step, cache, jnp.arange(seq))
    decoded = jnp.swapaxes(decoded, 0, 1)
    return full, decoded
