"""Autoregressive generation with KV cache (serving compute path).

One jitted `lax.scan` drives both prefill and decode: at step t the
input token is the prompt token (teacher-forced) while t < prompt_len,
else the previously sampled token — KV cache carried as flax 'cache'
variables, so per-token cost is O(1) in sequence length. This is the
in-framework inference engine behind `serve` replicas
(`recipes/serve_lm.py`); continuous batching lands in a later round.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def filter_logits(logits: jax.Array, top_k: jax.Array,
                  top_p: jax.Array) -> jax.Array:
    """Per-row top-k / nucleus (top-p) filtering, fixed-shape.

    logits: [..., V]; top_k int32 [...] (0 = off); top_p f32 [...]
    (1.0 = off). Filtered entries become -inf. Standard caveats: ties
    at the k-th logit all survive; the nucleus always keeps at least
    the argmax."""
    vocab = logits.shape[-1]
    while top_k.ndim < logits.ndim - 1:
        top_k = top_k[..., None]
        top_p = top_p[..., None]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(top_k - 1, 0, vocab - 1)[..., None],
        axis=-1)
    keep_k = jnp.where((top_k > 0)[..., None], logits >= kth, True)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Nucleus: keep a sorted token while the cumulative mass BEFORE it
    # is < p (the argmax always qualifies).
    sorted_keep = (cum - probs) < top_p[..., None]
    min_kept = jnp.min(jnp.where(sorted_keep, sorted_desc, jnp.inf),
                       axis=-1, keepdims=True)
    keep_p = jnp.where((top_p < 1.0)[..., None], logits >= min_kept,
                       True)
    return jnp.where(keep_k & keep_p, logits, -jnp.inf)


def sample_tokens(rng: jax.Array, logits: jax.Array, temps: jax.Array,
                  top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Per-row sampling: greedy where temps == 0, else categorical
    over temperature-scaled, top-k/top-p-filtered logits. With
    top_k=0 and top_p=1 this consumes the SAME rng stream as plain
    categorical (no behavior change for existing callers)."""
    while temps.ndim < logits.ndim - 1:
        temps = temps[..., None]
    # The filter costs a vocab sort per step: cond skips it at runtime
    # whenever NO live slot uses top-k/top-p (the common case), so the
    # unfiltered path stays as fast as plain categorical.
    need_filter = jnp.logical_or(jnp.any(top_k > 0),
                                 jnp.any(top_p < 1.0))
    # Temperature FIRST, then nucleus (the HF/vLLM/OpenAI order): the
    # nucleus is computed over the temperature-scaled distribution, so
    # low temperature narrows the kept set. Top-k is scale-invariant.
    scaled = logits / jnp.maximum(temps, 1e-6)[..., None]
    filtered = jax.lax.cond(
        need_filter, lambda: filter_logits(scaled, top_k, top_p),
        lambda: scaled)
    sampled = jax.random.categorical(rng, filtered, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def make_generate_fn(model, max_total_len: int,
                     temperature: float = 0.0,
                     eos_id: Optional[int] = None):
    """Returns jitted fn(params, prompt[B,P], rng) -> tokens [B, T].

    Output rows are prompt ++ generated, padded with eos/0 after eos.
    """
    assert max_total_len <= model.config.max_seq_len

    @functools.partial(jax.jit, static_argnums=())
    def generate(params, prompt: jax.Array, rng: jax.Array) -> jax.Array:
        batch, prompt_len = prompt.shape
        cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
            positions=jnp.zeros((batch, 1), jnp.int32), decode=True,
        )['cache']
        import flax.linen as nn
        # init *ran* a step (junk K/V at position 0): reset.
        cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

        def step(carry, t):
            cache, prev_token, rng = carry
            # Input: prompt token while inside the prompt, else sampled.
            in_prompt = t < prompt_len
            tok = jnp.where(
                in_prompt,
                jax.lax.dynamic_index_in_dim(
                    prompt, jnp.minimum(t, prompt_len - 1), axis=1,
                    keepdims=False),
                prev_token)
            positions = jnp.full((batch, 1), t, jnp.int32)
            logits, mutated = model.apply(
                {'params': params, 'cache': cache},
                tok[:, None], positions=positions, decode=True,
                mutable=['cache'])
            logits = logits[:, 0]  # [B, V]
            rng, sub = jax.random.split(rng)
            if temperature > 0:
                sampled = jax.random.categorical(
                    sub, logits / temperature, axis=-1)
            else:
                sampled = jnp.argmax(logits, axis=-1)
            sampled = sampled.astype(jnp.int32)
            return (mutated['cache'], sampled, rng), sampled

        init_token = jnp.zeros((batch,), jnp.int32)
        (_, _, _), sampled_seq = jax.lax.scan(
            step, (cache, init_token, rng),
            jnp.arange(max_total_len - 1))
        sampled_seq = jnp.swapaxes(sampled_seq, 0, 1)  # [B, T-1]

        # Assemble: positions < prompt_len come from the prompt;
        # position p >= prompt_len is the sample from step p-1.
        out = jnp.zeros((batch, max_total_len), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, prompt, (0, 0))
        positions = jnp.arange(max_total_len)[None, :]
        shifted = jnp.pad(sampled_seq, ((0, 0), (1, 0)))  # sample->pos+1
        out = jnp.where(positions >= prompt_len, shifted, out)

        if eos_id is not None:
            hit = jnp.cumsum(
                (out == eos_id) & (positions >= prompt_len), axis=1)
            keep = hit - ((out == eos_id) &
                          (positions >= prompt_len)).astype(hit.dtype) == 0
            out = jnp.where(keep, out, eos_id)
        return out

    return generate


def teacher_forced_logits(model, params, tokens: jax.Array
                          ) -> Tuple[jax.Array, jax.Array]:
    """Decode-mode logits for every position vs full-forward logits.

    Correctness harness: the cached incremental path must match the
    batched forward exactly (tests/unit_tests/test_generate.py).
    """
    batch, seq = tokens.shape
    full = model.apply({'params': params}, tokens)

    cache = model.init(
        jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
        positions=jnp.zeros((batch, 1), jnp.int32), decode=True)['cache']
    import flax.linen as nn
    cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

    def step(cache, t):
        tok = jax.lax.dynamic_slice_in_dim(tokens, t, 1, axis=1)
        positions = jnp.full((batch, 1), t, jnp.int32)
        logits, mutated = model.apply(
            {'params': params, 'cache': cache}, tok,
            positions=positions, decode=True, mutable=['cache'])
        return mutated['cache'], logits[:, 0]

    _, decoded = jax.lax.scan(step, cache, jnp.arange(seq))
    decoded = jnp.swapaxes(decoded, 0, 1)
    return full, decoded


def make_speculative_generate_fn(model, max_total_len: int,
                                 draft_k: int = 4, ngram: int = 2,
                                 eos_id: Optional[int] = None):
    """Greedy prompt-lookup speculative decoding.

    Drafts `draft_k` tokens per step by matching the last `ngram`
    generated tokens against earlier context (self-drafting — no draft
    model) and verifies the whole guess in ONE chunked forward pass
    through the cache (ops.chunked_cache_attention / the MLA absorbed
    chunk path). Accepted-prefix semantics make the output EXACTLY the
    greedy tokens of `make_generate_fn`, in between 1 and draft_k+1
    tokens per model call — large speedups on structured/repetitive
    text, never slower than +1 token per call. Greedy only (verification
    compares argmax); dense-cache models (paged pools not used here).

    Returns jitted fn(params, prompt [B, P], rng) -> tokens [B, T].
    """
    assert draft_k >= 1 and ngram >= 1
    # The verify chunk may write up to draft_k past the last kept token.
    assert max_total_len + draft_k + 1 <= model.config.max_seq_len + 1, (
        max_total_len, draft_k, model.config.max_seq_len)

    pad = draft_k + 1  # scratch tail so chunk writes stay in-bounds

    @jax.jit
    def generate(params, prompt: jax.Array, rng: jax.Array) -> jax.Array:
        del rng  # greedy
        batch, prompt_len = prompt.shape
        total = max_total_len + pad
        cache = model.init(
            jax.random.PRNGKey(0), jnp.zeros((batch, 1), jnp.int32),
            positions=jnp.zeros((batch, 1), jnp.int32), decode=True,
        )['cache']
        import flax.linen as nn
        cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))

        tokens = jnp.zeros((batch, total), jnp.int32)
        tokens = jax.lax.dynamic_update_slice(tokens, prompt, (0, 0))

        # PREFILL: the whole prompt in one chunk; its last logits give
        # the first generated token. prefill=True: the cache is empty,
        # so attention stays chunk-local (flash-eligible).
        positions = jnp.broadcast_to(jnp.arange(prompt_len),
                                     (batch, prompt_len))
        logits, mutated = model.apply(
            {'params': params, 'cache': cache}, prompt,
            positions=positions, decode=True, mutable=['cache'],
            prefill=True)
        cache = mutated['cache']
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        tokens = jax.vmap(
            lambda row, t: row.at[prompt_len].set(t))(tokens, first)
        length = jnp.full((batch,), prompt_len + 1, jnp.int32)

        # Sliding n-gram windows are recomputed per step from the
        # token buffer; windows fully inside the generated region only.
        n_windows = total - ngram  # window w covers [w, w+ngram)

        def draft(tokens_row, length_row):
            """Propose draft_k tokens following the most recent earlier
            occurrence of the row's trailing n-gram."""
            pattern = jax.lax.dynamic_slice(
                tokens_row, (length_row - ngram,), (ngram,))
            idx = jnp.arange(n_windows)
            windows = jnp.stack(
                [tokens_row[i:i + n_windows] for i in range(ngram)], -1)
            match = jnp.all(windows == pattern[None, :], axis=-1)
            # Only windows whose continuation starts before the tail:
            # w + ngram < length (strictly earlier occurrence).
            match &= idx + ngram < length_row
            any_match = jnp.any(match)
            w = jnp.where(match, idx, -1).max()
            src = jnp.where(any_match, w + ngram, length_row - 1)
            guess = jax.lax.dynamic_slice(tokens_row, (src,), (draft_k,))
            # No match: repeat the last token (worst case: 1 accept).
            last = tokens_row[length_row - 1]
            return jnp.where(any_match, guess,
                             jnp.full((draft_k,), last, jnp.int32))

        def cond(carry):
            tokens, cache, length = carry
            return jnp.any(length < max_total_len)

        def body(carry):
            tokens, cache, length = carry
            drafts = jax.vmap(draft)(tokens, length)        # [B, k]
            tokens = jax.vmap(
                lambda row, d, p: jax.lax.dynamic_update_slice(
                    row, d, (p,)))(tokens, drafts, length)
            # Verify chunk: [x_{L-1}, d_1..d_k] at positions L-1..L+k-1
            chunk = jax.vmap(
                lambda row, p: jax.lax.dynamic_slice(
                    row, (p - 1,), (draft_k + 1,)))(tokens, length)
            positions = (length - 1)[:, None] + jnp.arange(draft_k + 1)
            logits, mutated = model.apply(
                {'params': params, 'cache': cache}, chunk,
                positions=positions, decode=True, mutable=['cache'])
            cache = mutated['cache']
            y = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B,k+1]
            # Leading drafts matching the model's own greedy choice.
            accept = jnp.cumprod(
                (drafts == y[:, :-1]).astype(jnp.int32), axis=1)
            n_accept = accept.sum(axis=1)                       # [B]
            # Write the model's tokens (accepted prefix == drafts;
            # the first correction lands at L + n_accept).
            tokens = jax.vmap(
                lambda row, yy, p: jax.lax.dynamic_update_slice(
                    row, yy, (p,)))(tokens, y, length)
            advance = jnp.where(length < max_total_len,
                                n_accept + 1, 0)
            length = jnp.minimum(length + advance, max_total_len)
            return tokens, cache, length

        tokens, cache, length = jax.lax.while_loop(
            cond, body, (tokens, cache, length))
        out = tokens[:, :max_total_len]
        if eos_id is not None:
            positions = jnp.arange(max_total_len)[None, :]
            gen = positions >= prompt_len
            hit = jnp.cumsum((out == eos_id) & gen, axis=1)
            keep = hit - ((out == eos_id) & gen).astype(hit.dtype) == 0
            out = jnp.where(keep, out, eos_id)
        return out

    return generate
