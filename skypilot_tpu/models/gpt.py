"""GPT-2 (nanoGPT-class) in flax.linen with logical sharding axes.

Recipe model #1 (BASELINE.md config 1). Every parameter carries
logical axis names (`embed`, `mlp`, `heads`, `vocab`, ...) via
`nn.with_logical_partitioning`; `parallel/train.py` maps them onto a
mesh (DP×FSDP×TP) with `parallel/mesh.py` rules. Compute is bf16,
params f32 (standard mixed precision for the MXU).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.ops import attention as attention_ops

Dtype = Any


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # nanoGPT's padded GPT-2 vocab
    block_size: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    dropout_rate: float = 0.0
    # GPT-2's LayerNorm epsilon (HF layer_norm_epsilon); flax's default
    # is 1e-6 — matching 1e-5 matters for HF-checkpoint parity.
    norm_eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    # Logits match the compute dtype unless overridden. bf16 logits
    # halve the LM head's HBM traffic — at GPT-2 scale the [B,S,50k]
    # logits are the largest array in the step. The loss upcasts to f32
    # inside its logsumexp fusion (parallel/train.py), so softmax
    # numerics stay f32 without an f32 array in HBM.
    logits_dtype: Optional[Dtype] = None
    remat: bool = False
    # Paged KV cache for serving (see llama.LlamaConfig).
    kv_page_size: int = 16
    kv_total_pages: int = 128

    @classmethod
    def gpt2_124m(cls, **kw) -> 'GPTConfig':
        return cls(num_layers=12, num_heads=12, embed_dim=768, **kw)

    @classmethod
    def tiny(cls, **kw) -> 'GPTConfig':
        return cls(vocab_size=512, block_size=128, num_layers=2,
                   num_heads=4, embed_dim=128, **kw)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def max_seq_len(self) -> int:
        """Alias matching the llama/mixtral configs (serving engines
        read model.config.max_seq_len)."""
        return self.block_size

    def num_params(self) -> int:
        wpe = self.block_size * self.embed_dim
        wte = self.vocab_size * self.embed_dim
        per_layer = (12 * self.embed_dim ** 2 + 13 * self.embed_dim)
        return wte + wpe + self.num_layers * per_layer + 2 * self.embed_dim


def _dense(features: int, logical_axes, dtype, name: str,
           use_bias: bool = True) -> nn.Dense:
    return nn.Dense(
        features, dtype=dtype, use_bias=use_bias, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), logical_axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (logical_axes[-1],)))


class CausalSelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False) -> jax.Array:
        cfg = self.config
        batch, seq, _ = x.shape
        qkv = _dense(3 * cfg.embed_dim, ('embed', 'mlp'), cfg.dtype,
                     'c_attn')(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (batch, seq, cfg.num_heads, cfg.head_dim)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        def _page_vars():
            shape = (cfg.num_heads, cfg.kv_total_pages,
                     cfg.kv_page_size, cfg.head_dim)
            return (self.variable('cache', 'k_pages', jnp.zeros, shape,
                                  cfg.dtype),
                    self.variable('cache', 'v_pages', jnp.zeros, shape,
                                  cfg.dtype))

        if decode and seq > 1:
            # CHUNKED decode (same contract as models/llama.py):
            # `prefill` (static) = chunk-local attention; otherwise the
            # chunk attends the full history (speculative verification).
            assert positions is not None
            if page_indices is not None:
                from skypilot_tpu.ops import paged_attention as paged_ops
                k_pages, v_pages = _page_vars()
                k_pages.value, v_pages.value = paged_ops.write_kv_chunk(
                    k_pages.value, v_pages.value, k, v, positions,
                    page_indices)
                if prefill:
                    out = attention_ops.dot_product_attention(
                        q, k, v, causal=True)
                else:
                    out = paged_ops.paged_chunk_attention(
                        q, k_pages.value, v_pages.value, positions,
                        page_indices).astype(cfg.dtype)
            else:
                cached_k = self.variable(
                    'cache', 'cached_key', jnp.zeros,
                    (batch, cfg.block_size, cfg.num_heads, cfg.head_dim),
                    cfg.dtype)
                cached_v = self.variable(
                    'cache', 'cached_value', jnp.zeros,
                    (batch, cfg.block_size, cfg.num_heads, cfg.head_dim),
                    cfg.dtype)
                # `prefill` (static): empty-cache contract — attention
                # stays chunk-local (S x S, flash-eligible) instead of
                # S x block_size f32 scores.
                out, cached_k.value, cached_v.value = \
                    attention_ops.chunked_cache_attention(
                        q, k, v, cached_k.value, cached_v.value,
                        positions, chunk_only=prefill)
                out = out.astype(cfg.dtype)
        elif decode:
            # One token in, KV cache with a PER-ROW write index
            # (positions[:, 0]) — the shared serving-cache contract
            # (ops.attention.cached_decode_attention), so the generate
            # and continuous-batching engines drive GPT unchanged.
            assert positions is not None
            if page_indices is not None:
                # Paged KV (same contract as models/llama.py).
                from skypilot_tpu.ops import paged_attention as paged_ops
                k_pages, v_pages = _page_vars()
                k_pages.value, v_pages.value = paged_ops.write_kv(
                    k_pages.value, v_pages.value, k[:, 0], v[:, 0],
                    positions[:, 0], page_indices)
                out = paged_ops.paged_decode_attention(
                    q[:, 0], k_pages.value, v_pages.value,
                    lengths=positions[:, 0] + 1,
                    page_indices=page_indices)
                out = out[:, None].astype(cfg.dtype)
            else:
                cached_k = self.variable(
                    'cache', 'cached_key', jnp.zeros,
                    (batch, cfg.block_size, cfg.num_heads, cfg.head_dim),
                    cfg.dtype)
                cached_v = self.variable(
                    'cache', 'cached_value', jnp.zeros,
                    (batch, cfg.block_size, cfg.num_heads, cfg.head_dim),
                    cfg.dtype)
                out, cached_k.value, cached_v.value = \
                    attention_ops.cached_decode_attention(
                        q, k, v, cached_k.value, cached_v.value,
                        positions[:, 0])
                out = out.astype(cfg.dtype)
        else:
            q = nn.with_logical_constraint(q,
                                           ('batch', 'seq', 'heads', 'kv'))
            k = nn.with_logical_constraint(k,
                                           ('batch', 'seq', 'heads', 'kv'))
            v = nn.with_logical_constraint(v,
                                           ('batch', 'seq', 'heads', 'kv'))
            out = attention_ops.dot_product_attention(q, k, v, causal=True)
        out = out.reshape((batch, seq, cfg.embed_dim))
        out = _dense(cfg.embed_dim, ('mlp', 'embed'), cfg.dtype, 'c_proj')(out)
        if cfg.dropout_rate > 0:
            out = nn.Dropout(cfg.dropout_rate)(out, deterministic)
        return out


class MLP(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        cfg = self.config
        h = _dense(4 * cfg.embed_dim, ('embed', 'mlp'), cfg.dtype, 'c_fc')(x)
        h = nn.gelu(h)
        h = nn.with_logical_constraint(h, ('batch', 'seq', 'mlp'))
        h = _dense(cfg.embed_dim, ('mlp', 'embed'), cfg.dtype, 'c_proj')(h)
        if cfg.dropout_rate > 0:
            h = nn.Dropout(cfg.dropout_rate)(h, deterministic)
        return h


class Block(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False) -> jax.Array:
        cfg = self.config
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name=name,
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones_init(), ('norm',)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ('norm',)))
        x = x + CausalSelfAttention(cfg, name='attn')(
            ln('ln_1')(x), deterministic, positions=positions,
            decode=decode, page_indices=page_indices, prefill=prefill)
        x = x + MLP(cfg, name='mlp')(ln('ln_2')(x), deterministic)
        return nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))


def embed_tokens(params, tokens: jax.Array, cfg: GPTConfig) -> jax.Array:
    """Functional form of GPT's input embedding (wte + wpe over
    training positions). Shared with the pipeline trainer's stage-0 op
    (parallel/pipeline.py) so head/embedding changes cannot silently
    diverge between the sequential and pipelined paths."""
    wte = params['wte'].astype(cfg.dtype)
    wpe = params['wpe'].astype(cfg.dtype)
    return wte[tokens] + wpe[:tokens.shape[1]]


def final_norm_logits(params, x: jax.Array, cfg: GPTConfig) -> jax.Array:
    """Functional form of GPT's ln_f + tied LM head (the pipeline
    trainer's last-stage op)."""
    scale = params['ln_f']['scale'].astype(jnp.float32)
    bias = params['ln_f']['bias'].astype(jnp.float32)
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    x_n = ((x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps) * scale +
           bias).astype(cfg.dtype)
    return jnp.einsum('bse,ve->bsv', x_n, params['wte'].astype(cfg.dtype),
                      preferred_element_type=(cfg.logits_dtype or
                                              cfg.dtype))


class GPT(nn.Module):
    """GPT-2 decoder; __call__ returns logits [B, S, vocab].

    `return_hidden=True` returns the post-ln_f hidden states
    [B, S, embed] instead, skipping the LM-head matmul entirely — the
    trainer's fused blockwise cross-entropy (ops/fused_xent.py) takes
    it from there against the tied `wte` without ever materializing
    [B, S, vocab].
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 deterministic: bool = True,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        batch, seq = tokens.shape
        assert seq <= cfg.block_size, (seq, cfg.block_size)
        explicit_positions = positions is not None
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
        wte = self.param(
            'wte',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('vocab', 'table_embed')),
            (cfg.vocab_size, cfg.embed_dim), jnp.float32)
        wpe = self.param(
            'wpe',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.01), ('seq', 'table_embed')),
            (cfg.block_size, cfg.embed_dim), jnp.float32)
        # Training fast path: the default positions are a broadcast
        # arange — slice wpe instead of a batch-sized gather.
        pos_embed = (wpe.astype(cfg.dtype)[positions] if explicit_positions
                     else wpe.astype(cfg.dtype)[:seq])
        x = wte.astype(cfg.dtype)[tokens] + pos_embed
        x = nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))

        if cfg.remat:
            assert not decode, 'remat is a training-path option'
            # decode stays OUT of the remat arg list: jax.checkpoint
            # would trace the bool and break Python-level branching.
            block = nn.remat(Block, prevent_cse=False,
                             static_argnums=(2,))
            for i in range(cfg.num_layers):
                x = block(cfg, name=f'h_{i}')(x, deterministic, positions)
        else:
            for i in range(cfg.num_layers):
                x = Block(cfg, name=f'h_{i}')(x, deterministic,
                                              positions=positions,
                                              decode=decode,
                                              page_indices=page_indices,
                                              prefill=prefill)
        x = nn.LayerNorm(
            epsilon=cfg.norm_eps, dtype=cfg.dtype, name='ln_f',
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones_init(), ('norm',)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ('norm',)))(x)
        if return_hidden:
            return nn.with_logical_constraint(
                x, ('batch', 'seq', 'act_embed'))
        # Tied output head (nanoGPT style): logits = x @ wte^T. bf16
        # operands keep the matmul on the MXU's native bf16 path
        # (~4-8x the f32 rate); cfg.logits_dtype picks the output
        # precision (bf16 default — see GPTConfig).
        logits = jnp.einsum('bse,ve->bsv', x.astype(cfg.dtype),
                            wte.astype(cfg.dtype),
                            preferred_element_type=(cfg.logits_dtype or
                                                    cfg.dtype))
        return nn.with_logical_constraint(logits, ('batch', 'seq', 'vocab'))
