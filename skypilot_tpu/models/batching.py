"""Continuous batching: a slot-based decode engine for LM serving.

JetStream-shaped, TPU-first: all device work is fixed-shape jitted
functions. A fixed pool of `num_slots` decode slots shares one KV
cache; requests prefill into a free slot (prompt lengths bucketed to
limit recompiles) and then ride the shared decode loop, leaving as
they finish — new requests join WITHOUT waiting for the batch to
drain, which is what lifts serving throughput under ragged request
lengths (the reference orchestrates external engines with this
property; here the engine is in-framework, over models/llama.py's
per-row-position KV cache). With `speculative_k > 0` the loop runs
prompt-lookup verify chunks instead of single tokens: every slot
(greedy and sampled, paged and dense) commits 1..K+1 tokens per model
call, exactly preserving the non-speculative output distribution.

Two stall-free-scheduler mechanisms (Sarathi/vLLM split-fuse style):

  - CHUNKED PREFILL (`prefill_chunk=C`): an admitted prompt's suffix
    prefills in fixed C-token chunks (one compiled shape, plus small
    power-of-two tails) under a per-iteration token budget, with
    decode steps interleaved between chunks — one 4k-token prompt no
    longer stalls every active decode slot for a whole forward pass,
    and padding waste is bounded by the chunk, not a log2 bucket.
  - PIPELINED DECODE (`pipeline_decode`): decode round N+1 is
    dispatched (JAX async dispatch) BEFORE round N's tokens are
    fetched and committed, so host-side stop-detection/streaming
    overlaps device compute and the accelerator's dispatch queue
    stays non-empty. Greedy outputs are token-for-token identical to
    the unpipelined loop; lanes that finish mid-pipeline leave one
    junk write past their last committed position (the same
    write-before-read contract speculation relies on).

Use via `ContinuousBatchingEngine.submit(prompt) -> Future`, or the
HTTP server in recipes/serve_lm.py (--continuous-batching).
"""
from __future__ import annotations

import collections
import functools
import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.models.generate import sample_tokens
from skypilot_tpu.observability import catalog as _obs
from skypilot_tpu.observability import flight as flight_lib
from skypilot_tpu.observability import tracing
from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness.errors import (AdapterNotFoundError,
                                            DeadlineExceededError,
                                            EngineDeadError,
                                            QueueSaturatedError,
                                            SessionMigratedError)


def _bucket(n: int, cap: int) -> int:
    """Next power of two >= n (bounded): limits prefill recompiles."""
    b = 8
    while b < n:
        b *= 2
    return min(b, cap)


class PrefixCache:
    """Content-addressed KV page reuse across requests (the vLLM
    automatic-prefix-caching idea, TPU-paged form).

    Every FULL page of a prompt gets a chain key (hash of all tokens
    up to and including that page), so two requests sharing a system
    prompt map their common full pages to the SAME physical pages —
    admission skips recomputing them (prefill runs only the suffix)
    and the pool holds one copy. Pages of finished prompts stay
    RESIDENT but unreferenced (LRU), evicted back to the allocator
    only under pool pressure. Shared pages are never written: suffix
    prefill and decode both write at positions past the cached
    region, and the masked tail of a padded chunk lands in the trash
    page (the paged-KV contract, docs/internals.md §4).
    """

    def __init__(self, page_size: int,
                 metrics: Optional['_obs.EngineMetrics'] = None,
                 spill=None, fetch_pages=None, flight=None) -> None:
        self.page_size = page_size
        self.by_key: Dict[bytes, int] = {}
        self.key_of: Dict[int, bytes] = {}
        self.refs: Dict[int, int] = {}
        # Resident-but-unreferenced pages, oldest first (evictable).
        self.lru: 'collections.OrderedDict[int, None]' = \
            collections.OrderedDict()
        self.hits = 0       # pages served from cache
        self.misses = 0     # full prompt pages that had to be computed
        self.evictions = 0  # cached pages returned under pool pressure
        self._metrics = metrics  # owning engine's Prometheus bundle
        # Tiered cache (inference/kv_transfer.HostSpillTier): evicted
        # pages spill — exact device bytes, fetched by the engine's
        # `fetch_pages(pages) -> {leaf_path: page-major array}` — and
        # are restored on a later chain-key hit instead of recomputed.
        # None keeps the classic drop-on-evict behavior.
        self.spill = spill
        self._fetch_pages = fetch_pages
        self.spilled_pages = 0
        # Owning engine's flight recorder (observability/flight.py):
        # evict/spill decisions land in its ring. None = standalone.
        self._flight = flight

    @staticmethod
    def chain_keys(tokens, page_size: int,
                   salt: bytes = b'') -> List[bytes]:
        """One key per FULL page; key_i commits to ALL tokens through
        page i, so equal keys imply equal attention history. `salt`
        prefixes the chain (the adapter identity): once LoRA touches
        the k/v projections, a page's contents depend on WHICH
        adapter computed it — un-salted keys would serve one tenant's
        KV pages to another (inference/affinity.py re-derives the
        same salted keys for LB routing)."""
        import hashlib
        keys = []
        h = hashlib.sha256()
        if salt:
            h.update(salt)
        for i in range(len(tokens) // page_size):
            chunk = tokens[i * page_size:(i + 1) * page_size]
            h.update(np.asarray(chunk, np.int32).tobytes())
            keys.append(h.digest())
        return keys

    def lookup_acquire(self, keys: List[bytes],
                       record: bool = True) -> List[int]:
        """Longest cached prefix of `keys`; takes a reference on each
        returned page (pinned against eviction). `record=False`
        defers the hit/miss accounting to the caller (the engine's
        spill-restore path extends the prefix first, then records the
        post-restore truth — a restored page avoided the recompute
        exactly like a resident hit)."""
        pages = []
        for key in keys:
            page = self.by_key.get(key)
            if page is None:
                break
            pages.append(page)
            self.refs[page] = self.refs.get(page, 0) + 1
            self.lru.pop(page, None)
        if record:
            self.record_lookup(len(pages), len(keys) - len(pages))
        return pages

    def record_lookup(self, n_hits: int, n_misses: int) -> None:
        self.hits += n_hits
        self.misses += n_misses
        if self._metrics is not None:
            self._metrics.prefix_hits.inc(n_hits)
            self._metrics.prefix_misses.inc(n_misses)

    def acquire_page(self, key: bytes, page: int) -> None:
        """Adopt + immediately reference a page the engine just
        restored/imported into the pool under `key` (the
        insert-then-acquire composition, minus the LRU round trip)."""
        if not self.insert(key, page):
            raise ValueError(f'key already cached: {key.hex()[:12]}')
        self.lru.pop(page, None)
        self.refs[page] = self.refs.get(page, 0) + 1

    def release(self, pages: List[int]) -> None:
        for page in pages:
            self.refs[page] -= 1
            if self.refs[page] == 0:
                del self.refs[page]
                self.lru[page] = None  # newest evictable

    def insert(self, key: bytes, page: int) -> bool:
        """Adopt ownership of `page` under `key`; False = key already
        cached (caller keeps the page and releases it normally)."""
        if key in self.by_key:
            return False
        self.by_key[key] = page
        self.key_of[page] = key
        self.lru[page] = None
        return True

    def evict_into(self, allocator, need: int) -> None:
        """Return unreferenced cached pages to the allocator until it
        can serve `need` pages (or the evictable set is dry). With a
        spill tier the victims' device bytes are fetched in ONE
        batched gather and spilled (payload + scales + chain key)
        before their pages are released — restore on a later hit is
        bit-identical to the fresh compute."""
        deficit = need - allocator.free_pages
        if deficit <= 0:
            return
        victims: List[tuple] = []
        while len(victims) < deficit and self.lru:
            page, _ = self.lru.popitem(last=False)
            key = self.key_of.pop(page)
            del self.by_key[key]
            victims.append((key, page))
        if not victims:
            return
        if self.spill is not None and self._fetch_pages is not None:
            from skypilot_tpu.inference import kv_transfer
            try:
                blobs = self._fetch_pages([p for _, p in victims])
                per_page = kv_transfer.split_pages(blobs, len(victims))
                for (key, _page), blob in zip(victims, per_page):
                    self.spill.put(key, blob)
                    self.spilled_pages += 1
                    if self._metrics is not None:
                        self._metrics.kv_spill_pages.inc()
                if self._flight is not None:
                    self._flight.record('spill', pages=len(per_page))
            except Exception as e:  # pylint: disable=broad-except
                # Spilling is an optimization: a failed gather must
                # degrade to the classic drop-on-evict, never block
                # the admission that triggered the eviction.
                print(f'prefix cache: spill of {len(victims)} pages '
                      f'failed ({type(e).__name__}: {e}); dropping '
                      f'them instead', flush=True)
        for _, page in victims:
            allocator.release([page])
            self.evictions += 1
            if self._metrics is not None:
                self._metrics.prefix_evictions.inc()
        if self._flight is not None:
            self._flight.record('evict', pages=len(victims))


class ContinuousBatchingEngine:

    # Prometheus `engine` label values: one per engine instance in
    # this process (the serving runtime may run two — the main engine
    # plus the lazy stream engine).
    _instance_ids = itertools.count()

    # Thread-ownership contract, machine-checked by SKY008 (see
    # analysis/callgraph.py for the grammar and docs/internals.md
    # "Thread-ownership model"). Everything below is touched only by
    # the scheduler thread (_loop); cross-thread work hops through
    # run_on_scheduler. `cache` is STRICT ('scheduler!'): every
    # dispatch DONATES it, so even a read from another thread races
    # the dispatch that consumes the buffer. The scrape/HTTP threads'
    # racy snapshot reads of the non-strict counters and slot arrays
    # are deliberate (stale-but-consistent-enough stats) — reads of
    # non-strict attrs are allowed; writes are not.
    _STPU_OWNERS = {
        'cache': 'scheduler!',
        # slot arrays + per-slot bookkeeping
        'cur_token': 'scheduler', 'pos': 'scheduler',
        'active': 'scheduler', 'prefilling': 'scheduler',
        'prefill_frontier': 'scheduler', 'prompt_len': 'scheduler',
        'outputs': 'scheduler', 'limits': 'scheduler',
        'temps': 'scheduler', 'top_ks': 'scheduler',
        'top_ps': 'scheduler', 'stop_ids': 'scheduler',
        'on_tokens': 'scheduler', 'deadlines': 'scheduler',
        'slot_adapter': 'scheduler', 'slot_adapter_name': 'scheduler',
        '_prefill_order': 'scheduler', '_prefill_t0': 'scheduler',
        '_slot_ctx': 'scheduler',
        # paged-KV state (rebuilt by _reset_paging on the scheduler)
        'allocator': 'scheduler', 'page_table': 'scheduler',
        'owned_pages': 'scheduler', 'allocated_tokens': 'scheduler',
        'prefix_cache': 'scheduler', 'shared_pages': 'scheduler',
        'slot_keys': 'scheduler',
        # dispatch plumbing
        '_rng': 'scheduler', '_inflight': 'scheduler',
        '_prefill_fns': 'scheduler', '_scatter_fns': 'scheduler',
        '_cache_shardings': 'scheduler',
        # pipeline-stage dispatch state (PR 19): the per-group
        # in-flight ring, the per-stage jitted-fn cache, and the last
        # prefill pass's schedule bubble (scrape threads read the
        # float racily, like the counters).
        '_group_inflight': 'scheduler', '_stage_fns': 'scheduler',
        '_prefill_bubble': 'scheduler',
        # counters (scrape threads read these racily, on purpose)
        'decode_calls': 'scheduler', 'tokens_committed': 'scheduler',
        'preemptions': 'scheduler', 'prefill_chunks_run': 'scheduler',
        'decode_stall_s': 'scheduler',
        'last_prefill_tokens': 'scheduler',
        'kv_restored_pages': 'scheduler',
        'kv_restore_lookups': 'scheduler',
        'kv_restore_hits': 'scheduler',
        'deadline_exceeded': 'scheduler', 'engine_restarts': 'scheduler',
        '_soft_errors': 'scheduler',
        # live-migration counters (PR 20): evacuated sessions and the
        # subset that shipped a packed KV chain with them
        'sessions_evacuated': 'scheduler',
        'chains_evacuated': 'scheduler',
    }

    def __init__(self, model, params, *, num_slots: int = 8,
                 max_total_len: int = 256, temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 paged: Optional[bool] = None,
                 prefix_caching: bool = True,
                 speculative_k: int = 0, spec_ngram: int = 2,
                 spec_lookback: int = 512,
                 decode_chunk: int = 1,
                 prefill_chunk: int = 0,
                 prefill_budget: int = 0,
                 pipeline_decode: Optional[bool] = None,
                 max_queue_requests: int = 0,
                 max_queue_tokens: int = 0,
                 adapter_store=None,
                 kv_spill_bytes: int = 0,
                 kv_cold_dir: Optional[str] = None,
                 mesh=None) -> None:
        assert max_total_len <= model.config.max_seq_len
        # Mesh-sharded device state (parallel/serving.py): with a
        # mesh, the KV cache is EXPLICITLY placed — paged pool values
        # shard their kv-heads axis over `tensor` (GQA remainder
        # rule: replicate when heads don't divide), scale pages
        # replicate — and every jitted dispatch pins the donated
        # cache's out_sharding, so an N-chip mesh holds ~N x the
        # pages at fixed per-chip HBM with zero per-step resharding.
        self.mesh = mesh
        self.mesh_devices = (int(mesh.devices.size)
                             if mesh is not None else 1)
        self._cache_shardings = None
        # Pipeline stages (PR 19): a (stage, tensor) mesh splits the
        # model's layers into contiguous per-stage ranges; each stage
        # is a tensor-parallel submesh with its OWN params, cache and
        # jitted dispatches, chained host-side per round. 1 = the
        # classic single-program engine (tensor-only or one device).
        self.stages = (int(mesh.shape.get('stage', 1))
                       if mesh is not None else 1)
        # Multi-LoRA serving (inference/adapters.py): each slot may
        # carry an adapter id into the shared dispatch; the model
        # gathers per-slot A/B factors from the store's stacked
        # tensors. None = base-model-only engine (no LoRA code runs).
        if adapter_store is not None and not lora_lib.supports(model):
            raise ValueError(
                f'{type(model).__name__} has no LoRA forward path; '
                f'serve adapters with a Llama-family model or drop '
                f'--adapter-dir')
        self.adapter_store = adapter_store
        # Chunked decode: N single-token steps in ONE jitted lax.scan
        # dispatch (the serving analog of the trainer's multi-step) —
        # outputs are BIT-IDENTICAL to step-by-step because the rng
        # split chain is the same, and post-limit/post-eos junk writes
        # follow the speculative write-before-read contract. Pays on
        # dispatch-overhead-bound hosts (TPU-over-relay: ~100ms per
        # dispatch vs ~ms of decode compute); costs up to N-1 wasted
        # steps per finishing request and batches admission at chunk
        # boundaries. Mutually exclusive with speculation (verify
        # chunks already amortize dispatches).
        assert decode_chunk >= 1
        assert not (decode_chunk > 1 and speculative_k), (
            'decode_chunk composes with the plain decode loop only; '
            'speculative verify chunks already commit multiple tokens '
            'per dispatch')
        self.decode_chunk = decode_chunk
        if decode_chunk > 1:
            assert max_total_len + decode_chunk <= \
                model.config.max_seq_len, (
                    f'decode_chunk={decode_chunk} writes up to that '
                    f'many positions past a finishing request: '
                    f'max_total_len({max_total_len}) + chunk must be '
                    f'<= max_seq_len({model.config.max_seq_len})')
        if speculative_k:
            # Verification chunks write up to K past the last kept
            # token — same headroom contract as the one-shot
            # speculative engine (models/generate.py).
            assert max_total_len + speculative_k <= \
                model.config.max_seq_len, (
                    f'speculative_k={speculative_k} needs headroom: '
                    f'max_total_len({max_total_len}) + K must be <= '
                    f'max_seq_len({model.config.max_seq_len})')
        # Chunked prefill: the admitted prompt's suffix runs in
        # fixed-size chunks under a per-iteration token budget, with
        # decode steps interleaved — instead of one whole-prompt
        # forward pass that stalls every active decode slot.
        # prefill_chunk=0 keeps the single-shot path (whole suffix in
        # one log2-bucketed dispatch, budget unbounded).
        if prefill_chunk < 0:
            raise ValueError(
                f'prefill_chunk must be >= 0, got {prefill_chunk}')
        self.prefill_chunk = prefill_chunk
        if prefill_chunk and 0 < prefill_budget < prefill_chunk:
            raise ValueError(
                f'prefill_budget={prefill_budget} < prefill_chunk='
                f'{prefill_chunk}: the budget is spent in whole '
                f'chunks, so no chunk could ever be issued')
        # Effective tokens-per-iteration cap; default = one chunk per
        # loop iteration (maximal decode interleaving).
        self.prefill_budget = ((prefill_budget or prefill_chunk)
                               if prefill_chunk else 0)
        # One-step host/device pipelining: dispatch decode round N+1
        # before committing round N, so stop-detection/streaming
        # overlaps device compute. Composes with the PLAIN decode loop
        # only — verify chunks and decode chunks already amortize
        # dispatches and fetch multi-token results the host must
        # reconcile synchronously. Auto mode (None) enables it exactly
        # when the plain loop runs.
        if pipeline_decode and (speculative_k or decode_chunk > 1):
            raise ValueError(
                'pipeline_decode composes with the plain decode loop '
                'only; speculative_k and decode_chunk dispatch '
                'multi-token rounds that are committed synchronously '
                '(set pipeline_decode=None/False with those modes)')
        self.pipeline_decode = (not speculative_k and decode_chunk == 1
                                if pipeline_decode is None
                                else bool(pipeline_decode))
        self.model = model
        self.params = params
        self.num_slots = num_slots
        self.max_total_len = max_total_len
        self.temperature = temperature
        self.eos_id = eos_id
        self.spec_k = speculative_k
        self.spec_ngram = spec_ngram
        self.spec_lookback = spec_lookback

        # Paged KV cache (vLLM-style; ops/paged_attention.py): K/V live
        # in a shared physical page pool sized for the AGGREGATE live
        # tokens instead of num_slots * max_total_len, with host-side
        # incremental page allocation. Auto-on for models that declare
        # kv_page_size/kv_total_pages (llama/gpt/mixtral) when the
        # pool can hold a full-depth sequence.
        cfg_page = getattr(model.config, 'kv_page_size', 0)
        cfg_pool = getattr(model.config, 'kv_total_pages', 0)
        # Speculative verify chunks write K tokens — and decode chunks
        # N-1 tokens — past the last committed one: the pool and each
        # row's page table carry that headroom.
        self._write_lookahead = max(self.spec_k, self.decode_chunk - 1)
        pool_ok = (cfg_page > 0 and cfg_pool > 0 and
                   (cfg_pool - 1) * cfg_page >=
                   max_total_len + self._write_lookahead)
        if paged is None:
            # Auto-on only when the pool can hold at least ONE
            # full-depth sequence — a small default pool must not
            # silently cap servable lengths below max_total_len (the
            # dense path has no such cap).
            paged = pool_ok
        elif paged and not pool_ok:
            raise ValueError(
                f'paged=True but kv_total_pages={cfg_pool} x '
                f'kv_page_size={cfg_page} cannot hold one '
                f'max_total_len={max_total_len} sequence '
                f'(+{self._write_lookahead} chunk-write headroom; '
                f'usable {(max(cfg_pool - 1, 0)) * cfg_page} tokens; '
                f'page 0 is reserved).')
        self.paged = paged
        # KV storage format (models/llama.py LlamaConfig.kv_dtype):
        # int8 pages + parallel scale arrays. Quantization lives
        # entirely inside the model's cache variables and the
        # paged-attention ops — the scheduler's page bookkeeping
        # (alloc/free/prefix sharing/chain keys) is format-blind.
        self.kv_dtype = getattr(model.config, 'kv_dtype', 'bf16')
        if self.kv_dtype not in ('bf16', 'int8'):
            raise ValueError(
                f'unsupported kv_dtype {self.kv_dtype!r} '
                f"(choices: 'bf16', 'int8')")
        if self.kv_dtype == 'int8' and not self.paged:
            raise ValueError(
                'kv_dtype=int8 requires the paged KV cache: the '
                'dense per-slot cache has no scale storage (size the '
                'kv page pool to hold max_total_len, or serve bf16)')
        if self.paged:
            self.page_size = cfg_page
            self.total_pages = cfg_pool
            self.pages_per_seq = -(
                -(max_total_len + self._write_lookahead)
                // self.page_size)
        # Ways the KV-heads axis actually shards (1 = replicated
        # pool — single device, or the GQA remainder rule fired).
        # Surfaced in /stats `page_pool.shard_ways` so operators can
        # see whether the mesh is buying pool capacity.
        self.kv_shard_ways = 1
        if mesh is not None:
            from skypilot_tpu.parallel import serving as _tp_serving
            self.kv_shard_ways = _tp_serving.kv_shard_ways(
                int(getattr(model.config, 'num_kv_heads', 0) or 0),
                int(mesh.shape.get('tensor', 1)))
        # Staged build: split the param tree by stage and place each
        # stage on its tensor submesh (parallel/serving.py
        # build_staged_serving). From here on `self.params` and
        # `self.cache` are LISTS of per-stage trees — a list of
        # pytrees is itself a pytree, so the tree-walking helpers
        # (kv_cache_bytes, _cache_lost, weight accounting) apply
        # unchanged.
        self._stage_models: List[Any] = []
        self._stage_submeshes: List[Any] = []
        self._stage_ranges: List[Any] = []
        self._stage_replicated: List[Any] = []
        self._stage_fns: Dict[Any, Any] = {}
        if self.stages > 1:
            if not self.paged:
                raise ValueError(
                    'stages > 1 requires the paged KV cache: the '
                    'per-stage pool split is a split of the page '
                    'pool (declare kv_page_size/kv_total_pages)')
            if self.decode_chunk > 1:
                raise ValueError(
                    'decode_chunk > 1 does not compose with stages: '
                    'the chunk lax.scan would cross submeshes inside '
                    'one jit (use pipeline_decode, the staged engine '
                    'overlaps rounds across stages instead)')
            if self.num_slots % self.stages:
                raise ValueError(
                    f'num_slots={self.num_slots} must divide evenly '
                    f'into stages={self.stages} slot groups (the '
                    f'S-deep decode ring partitions slots per stage)')
            from skypilot_tpu.inference import quant as quant_lib
            if isinstance(model, quant_lib.QuantizedModel) or \
                    quant_lib.is_quantized(params):
                raise ValueError(
                    'int8 WEIGHTS do not compose with stages yet '
                    '(int8 KV pages do): serve quantized weights '
                    'tensor-only, or bf16 weights staged')
            from jax.sharding import NamedSharding, PartitionSpec
            (self._stage_models, params, self._stage_submeshes,
             self._stage_ranges) = _tp_serving.build_staged_serving(
                 model, params, mesh)
            self._stage_replicated = [
                NamedSharding(sub, PartitionSpec())
                for sub in self._stage_submeshes]
            self.params = params
        self.prefix_caching = bool(prefix_caching and self.paged)
        self.prefix_cache: Optional[PrefixCache] = None  # set per reset
        # Tiered prefix cache: evicted pages spill to a bounded
        # host-RAM LRU (optionally backed by a cold directory / gs://
        # prefix) and restore bit-identically on a chain-key hit.
        # The tier OUTLIVES engine resets (content-addressed host
        # bytes stay valid across a crash-only cache rebuild).
        if (kv_spill_bytes or kv_cold_dir) and not self.prefix_caching:
            raise ValueError(
                'kv_spill_bytes/kv_cold_dir need the paged engine '
                'with prefix caching enabled (the spill tier stores '
                'evicted prefix-cache pages)')
        from skypilot_tpu.inference import kv_transfer as _kvt
        self.spill_tier = _kvt.make_spill_tier(kv_spill_bytes,
                                               kv_cold_dir)
        # Restore accounting (the spill tier's own stats count host
        # lookups; these count the engine-level outcome).
        self.kv_restored_pages = 0
        self.kv_restore_lookups = 0
        self.kv_restore_hits = 0

        # Prometheus instruments (observability/catalog.py), labeled
        # by engine instance; counters tick at the event sites below,
        # gauges refresh in update_metric_gauges() at scrape time.
        self.engine_id = str(next(self._instance_ids))
        self.metrics = _obs.EngineMetrics(self.engine_id)
        self.metrics.num_slots.set(num_slots)
        self._weight_bytes: Optional[int] = None  # lazy (roofline)
        # Flight recorder (observability/flight.py): every scheduler
        # decision lands in this bounded ring, unconditionally —
        # served at /debug/flight and snapshotted to a file on
        # reset/death. Single-writer (the scheduler thread);
        # deliberately lock-free, so SKY003 does not apply to it.
        self.flight = flight_lib.FlightRecorder(
            name=f'engine{self.engine_id}')

        # _fresh_cache is the single paging-reset point (also the
        # error-recovery path).
        self.cache = self._fresh_cache()

        # Host-side slot bookkeeping (device work stays fixed-shape).
        # A slot is OCCUPIED when `prefilling` (admitted, prompt
        # suffix still being written into the cache chunk by chunk)
        # or `active` (prefilled, riding the shared decode loop).
        self.cur_token = np.zeros((num_slots,), np.int32)
        self.pos = np.zeros((num_slots,), np.int32)
        self.active = np.zeros((num_slots,), bool)
        self.prefilling = np.zeros((num_slots,), bool)
        # Next prompt position the slot's prefill will write. While a
        # slot prefills, `pos` tracks this frontier too, so the decode
        # loop's junk write for the (inactive) lane lands at a
        # position the NEXT chunk overwrites before attending.
        self.prefill_frontier = np.zeros((num_slots,), np.int32)
        self.prompt_len = np.zeros((num_slots,), np.int32)
        self.outputs: List[List[int]] = [[] for _ in range(num_slots)]
        self.futures: List[Optional[Future]] = [None] * num_slots
        self.limits = np.zeros((num_slots,), np.int32)
        self.temps = np.zeros((num_slots,), np.float32)
        self.top_ks = np.zeros((num_slots,), np.int32)   # 0 = off
        self.top_ps = np.ones((num_slots,), np.float32)  # 1 = off
        self.stop_ids: List[frozenset] = [frozenset()] * num_slots
        self.on_tokens: List[Optional[Callable[[int], None]]] = \
            [None] * num_slots
        # Per-slot absolute (monotonic) deadline; 0 = none. The
        # scheduler reaps expired slots between rounds so a
        # deadline-bearing request cannot hold a slot past it.
        self.deadlines = np.zeros((num_slots,), np.float64)
        # Per-slot adapter: device-store row id (0 = base model) and
        # the registry name (for refcount release + token metrics).
        self.slot_adapter = np.zeros((num_slots,), np.int32)
        self.slot_adapter_name: List[Optional[str]] = [None] * num_slots
        # Prefilling slots in admission order: the scheduler finishes
        # the oldest admission's prefill first (FCFS — completing one
        # prompt starts its decode sooner than round-robining all).
        self._prefill_order: 'collections.deque' = collections.deque()
        self._prefill_t0 = [0.0] * num_slots
        # Per-slot distributed-tracing context
        # (observability/tracing.py); None = request not sampled.
        # Scheduler-thread owned, like the other slot arrays.
        self._slot_ctx: List[Optional[Any]] = [None] * num_slots

        # Observability: model calls vs tokens committed (speculation
        # quality = tokens_committed / decode_calls, 1.0..K+1), and
        # page-pressure preemptions (the /stats + /metrics signal that
        # the pool is undersized for the offered load).
        self.decode_calls = 0
        self.tokens_committed = 0
        self.preemptions = 0
        self.prefill_chunks_run = 0
        self.decode_stall_s = 0.0        # host blocked on device_get
        self.last_prefill_tokens = 0     # budget spent, last iteration
        # Live migration (PR 20): sessions evacuated off this engine
        # (drain / preemption notice / rebalance) and the subset whose
        # committed KV chain was packed for shipment.
        self.sessions_evacuated = 0
        self.chains_evacuated = 0

        # Admission control (load shedding): 0 = unbounded. submit()
        # raises QueueSaturatedError instead of queueing past these —
        # a saturated replica answers 429 in microseconds rather than
        # parking requests it will serve after their callers gave up.
        self.max_queue_requests = int(max_queue_requests)
        self.max_queue_tokens = int(max_queue_tokens)
        self._shed_lock = threading.Lock()
        self._queued_tokens_n = 0   # prompt tokens in _queue + _ready
        self.requests_shed = 0
        self.deadline_exceeded = 0
        self.engine_restarts = 0
        self._soft_errors = 0       # consecutive cache-intact errors
        # Crash-only: a dead scheduler thread flips this instead of
        # hanging clients (submit fails fast; /readyz reports 503).
        self._dead = threading.Event()

        self._chunk_decode = (self._make_chunk_decode_fn()
                              if self.decode_chunk > 1 else None)
        # Client-abandoned requests (disconnected stream consumers):
        # applied on the scheduler thread between rounds.
        self._cancel_requests: set = set()
        self._cancel_lock = threading.Lock()
        self._queue: 'queue.Queue' = queue.Queue()
        # Control operations (KV chain export/import) hop onto the
        # scheduler thread here: ALL device work — including page
        # gather/scatter — runs between decode rounds on the one
        # thread that owns self.cache (touching a donated buffer from
        # an HTTP thread would race the dispatch that consumes it).
        self._control: 'queue.Queue' = queue.Queue()
        # Jitted page-scatter fns keyed by (padded) chain length.
        self._scatter_fns: Dict[int, Any] = {}
        # FCFS admission order, owned by the scheduler thread: requests
        # drain from _queue into _ready; a stalled (page-pressure) or
        # preempted request returns to the HEAD so later arrivals can't
        # starve it (vLLM-style head-of-line blocking).
        self._ready: 'collections.deque' = collections.deque()
        self._rng = jax.random.PRNGKey(0)
        self._prefill_fns: Dict[Any, Any] = {}
        self._decode = (self._make_spec_decode_fn() if self.spec_k
                        else self._make_decode_fn())
        # Pipelined decode: the dispatched-but-not-committed round
        # (device token array + the host state it was built from).
        self._inflight: Optional[Dict[str, Any]] = None
        # Staged decode ring: one in-flight round per slot GROUP
        # (contiguous num_slots/stages slice) — up to S rounds in
        # flight, each occupying a different stage of the chain.
        self._group_inflight: List[Optional[Dict[str, Any]]] = \
            [None] * self.stages
        # Closed-form bubble fraction of the last staged prefill
        # pass's chunk-microbatch schedule ((S-1)/(M+S-1); 0.0 for
        # unstaged engines) — the prefill_bubble_fraction gauge.
        self._prefill_bubble = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(  # stpu: thread[scheduler]
            target=self._loop, daemon=True)
        self._thread.start()

    def _reset_paging(self) -> None:
        from skypilot_tpu.ops import paged_attention as paged_ops
        self.allocator = paged_ops.PageAllocator(self.total_pages,
                                                 self.pages_per_seq)
        # Physical page 0 is the TRASH page: unallocated table entries
        # point at it, so junk writes (inactive slots, padded prefill
        # tails, exhausted slots) can never corrupt a live page.
        trash = self.allocator.allocate(1)
        assert trash == [0], trash
        self.page_table = np.zeros((self.num_slots, self.pages_per_seq),
                                   np.int32)
        self.owned_pages: List[List[int]] = [
            [] for _ in range(self.num_slots)]
        self.allocated_tokens = np.zeros((self.num_slots,), np.int32)
        # Prefix caching (vLLM APC): per-slot shared (read-only) page
        # refs + the prompt's chain keys for promotion on completion.
        # PrefixCache invokes fetch_pages only from restore paths that
        # run on the engine thread, hence the role pin.
        self.prefix_cache = (PrefixCache(
            self.page_size, metrics=self.metrics,
            spill=self.spill_tier,
            fetch_pages=self._gather_page_blobs,  # stpu: role[scheduler]
            flight=self.flight)
            if self.prefix_caching else None)
        self.shared_pages: List[List[int]] = [
            [] for _ in range(self.num_slots)]
        self.slot_keys: List[List[bytes]] = [
            [] for _ in range(self.num_slots)]

    def _fresh_cache(self):
        """Zeroed KV cache for the slot pool. Also the recovery path:
        prefill/decode DONATE the cache buffer, so after a failed
        device execution the old buffer is gone and must be rebuilt."""
        import flax.linen as nn
        if self.stages > 1:
            return self._fresh_staged_cache()
        kwargs = {}
        if self.paged:
            self._reset_paging()
            kwargs['page_indices'] = jnp.zeros(
                (self.num_slots, self.pages_per_seq), jnp.int32)
        cache = self.model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((self.num_slots, 1), jnp.int32),
            positions=jnp.zeros((self.num_slots, 1), jnp.int32),
            decode=True, **kwargs)['cache']
        # init *ran* a step; zero it (same contract as generate.py).
        cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))
        if self.mesh is not None:
            # Explicit placement: the pool starts on its declared
            # shardings and every dispatch's out_shardings keeps the
            # donated buffer there — the layout survives resets too.
            from skypilot_tpu.parallel import serving as _tp_serving
            if self._cache_shardings is None:
                self._cache_shardings = \
                    _tp_serving.serving_cache_shardings(cache,
                                                        self.mesh)
            cache = jax.device_put(cache, self._cache_shardings)
        return cache

    def _pin_cache_out(self, *tail, stage=None):
        """jit kwargs pinning a dispatch's donated-cache OUTPUT to
        the engine's explicit cache shardings (mesh engines; {} on
        single-device). Inputs arrive committed — the cache via
        _fresh_cache's device_put, params via
        shard_params_for_serving — so in_shardings are inferred from
        the operands; pinning the output closes the loop: the
        donated pool keeps its layout step over step and GSPMD never
        inserts a resharding collective on it (asserted by the
        pool_collective_lines guard test). `tail` holds one None per
        non-cache output — unconstrained, XLA places them. `stage`
        selects ONE stage's shardings for a staged engine's
        per-stage dispatch (the same zero-resharding pin, applied on
        that stage's submesh)."""
        if self._cache_shardings is None:
            return {}
        sh = (self._cache_shardings if stage is None
              else self._cache_shardings[stage])
        if tail:
            return {'out_shardings': (sh, *tail)}
        return {'out_shardings': sh}

    # -- staged (tensor x pipeline) engine ----------------------------------
    def _fresh_staged_cache(self):
        """Per-stage zeroed caches, one tree per stage submesh. Each
        stage's model owns only its [lo, hi) layers, so its cache tree
        holds the FULL page pool for just those layers — the per-stage
        pool split that lets an S-stage T-way mesh hold ~S·T x the
        pages at fixed per-chip HBM. Within a stage the placement is
        exactly the PR 15 tensor-parallel layout on the submesh."""
        import flax.linen as nn
        from skypilot_tpu.parallel import serving as _tp_serving
        self._reset_paging()
        cfg = self.model.config
        page_kw = {'page_indices': jnp.zeros(
            (self.num_slots, self.pages_per_seq), jnp.int32)}
        first_shardings = self._cache_shardings is None
        if first_shardings:
            self._cache_shardings = []
        caches = []
        for s, sm in enumerate(self._stage_models):
            x = (jnp.zeros((self.num_slots, 1), jnp.int32) if s == 0
                 else jnp.zeros((self.num_slots, 1, cfg.embed_dim),
                                cfg.dtype))
            cache = sm.init(
                jax.random.PRNGKey(0), x,
                positions=jnp.zeros((self.num_slots, 1), jnp.int32),
                decode=True, **page_kw)['cache']
            cache = jax.tree.map(jnp.zeros_like, nn.meta.unbox(cache))
            if first_shardings:
                self._cache_shardings.append(
                    _tp_serving.serving_cache_shardings(
                        cache, self._stage_submeshes[s]))
            caches.append(jax.device_put(cache,
                                         self._cache_shardings[s]))
        return caches

    def _stage_decode_fn(self, s: int):
        """One stage's jitted decode dispatch: stage 0 maps tokens ->
        hidden, middle stages hidden -> hidden, the last stage samples
        tokens from its logits. Shape-polymorphic through retracing —
        the plain loop calls with seq=1, the speculative verify chunk
        with seq=K+1, the group ring with batch=num_slots/stages."""
        key = ('decode', s)
        if key in self._stage_fns:
            return self._stage_fns[key]
        sm = self._stage_models[s]
        if s == self.stages - 1:

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._pin_cache_out(None, stage=s))
            def stage_fn(params, cache, x, positions, temps, top_ks,
                         top_ps, rng, page_indices, lora=None,
                         adapter_ids=None):
                extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                         if lora is not None else {})
                logits, mutated = sm.apply(
                    {'params': params, 'cache': cache}, x,
                    positions=positions, decode=True,
                    mutable=['cache'], page_indices=page_indices,
                    **extra)
                if logits.shape[1] == 1:
                    out = sample_tokens(rng, logits[:, 0], temps,
                                        top_ks, top_ps)
                else:           # verify chunk: [B, K+1, V]
                    out = sample_tokens(rng, logits, temps, top_ks,
                                        top_ps)
                return mutated['cache'], out
        else:

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._pin_cache_out(None, stage=s))
            def stage_fn(params, cache, x, positions, page_indices,
                         lora=None, adapter_ids=None):
                extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                         if lora is not None else {})
                hidden, mutated = sm.apply(
                    {'params': params, 'cache': cache}, x,
                    positions=positions, decode=True,
                    mutable=['cache'], page_indices=page_indices,
                    **extra)
                return mutated['cache'], hidden

        self._stage_fns[key] = stage_fn
        return stage_fn

    def _make_staged_decode_chain(self):
        """Host-side stage chain with the SAME signature as the
        single-mesh jitted decode/spec fns, so every dispatch call
        site works unchanged. Each stage's dispatch is async; the
        activation hops submeshes through an explicit device_put (the
        ONLY cross-stage traffic — per-stage pools never exchange a
        byte), and the ring-fed token array hops back to stage 0 the
        same way. The host never blocks inside the chain."""

        def decode_chain(params, cache, cur, pos, temps, top_ks,
                         top_ps, rng, page_indices=None, lora=None,
                         adapter_ids=None):
            cur = jnp.asarray(cur)
            pos = jnp.asarray(pos)
            if cur.ndim == 1:           # plain decode: seq=1
                x = cur[:, None]
                positions = pos[:, None]
            else:                       # speculative verify chunk
                x = cur
                positions = (pos[:, None] +
                             jnp.arange(cur.shape[1],
                                        dtype=jnp.int32)[None, :])
            lora_kw = ({'lora': lora, 'adapter_ids': adapter_ids}
                       if lora is not None else {})
            caches = []
            out = None
            for s in range(self.stages):
                x = jax.device_put(x, self._stage_replicated[s])
                fn = self._stage_decode_fn(s)
                if s < self.stages - 1:
                    new_cache, x = fn(params[s], cache[s], x,
                                      positions, page_indices,
                                      **lora_kw)
                else:
                    new_cache, out = fn(params[s], cache[s], x,
                                        positions, temps, top_ks,
                                        top_ps, rng, page_indices,
                                        **lora_kw)
                caches.append(new_cache)
            return caches, out

        # The chain only ever runs inside scheduler-thread dispatch
        # paths (it IS self._decode); pin the escape so the per-stage
        # fn cache's ownership holds.
        return decode_chain  # stpu: role[scheduler]

    def _stage_prefill_fn(self, s: int, bucket_len: int, fresh: bool):
        """One stage's jitted prefill-chunk dispatch (batch 1, a
        log2-bucketed chunk). `fresh` distinguishes a from-empty
        prefill (chunk-local attention) from a suffix chunk that
        attends the full resident history through the page table —
        the same prefill=True/False split as the single-mesh fns."""
        key = ('prefill', s, bucket_len, fresh)
        if key in self._stage_fns:
            return self._stage_fns[key]
        sm = self._stage_models[s]
        if s == self.stages - 1:

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._pin_cache_out(None, stage=s))
            def stage_fn(params, cache, x, positions, plen, page_row,
                         lora=None, adapter_ids=None):
                extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                         if lora is not None else {})
                logits, mutated = sm.apply(
                    {'params': params, 'cache': cache}, x,
                    positions=positions, decode=True,
                    mutable=['cache'], page_indices=page_row,
                    prefill=fresh, **extra)
                # The continuation samples from the LAST REAL chunk
                # position, not the padded tail.
                last = jax.lax.dynamic_index_in_dim(
                    logits[0].astype(jnp.float32), plen - 1, axis=0,
                    keepdims=False)
                return mutated['cache'], last
        else:

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._pin_cache_out(None, stage=s))
            def stage_fn(params, cache, x, positions, page_row,
                         lora=None, adapter_ids=None):
                extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                         if lora is not None else {})
                hidden, mutated = sm.apply(
                    {'params': params, 'cache': cache}, x,
                    positions=positions, decode=True,
                    mutable=['cache'], page_indices=page_row,
                    prefill=fresh, **extra)
                return mutated['cache'], hidden

        self._stage_fns[key] = stage_fn
        return stage_fn

    def _staged_prefill_chain(self, bucket_len: int, fresh: bool):
        """Host-side prefill chain matching the single-mesh
        `_prefill_fn` (fresh=True) / `_prefill_suffix_fn`
        (fresh=False) signatures. Dispatches are async, so
        successive chunk microbatches PIPELINE across stages: chunk
        c+1's stage-0 pass runs while chunk c occupies stage 1 — the
        chunked-prefill stream is the microbatch stream, no separate
        schedule executor needed (the schedule's closed form only
        prices the bubble, see _prefill_work)."""

        def chain(params, cache, x_tokens, plen, *rest, lora=None,
                  adapter_ids=None):
            if fresh:
                (page_row,) = rest
                positions = jnp.arange(bucket_len,
                                       dtype=jnp.int32)[None, :]
            else:
                offset, page_row = rest
                positions = (offset +
                             jnp.arange(bucket_len,
                                        dtype=jnp.int32))[None, :]
            lora_kw = ({'lora': lora, 'adapter_ids': adapter_ids}
                       if lora is not None else {})
            x = jnp.asarray(x_tokens)[None, :]
            caches = []
            last = None
            for s in range(self.stages):
                x = jax.device_put(x, self._stage_replicated[s])
                fn = self._stage_prefill_fn(s, bucket_len, fresh)
                if s < self.stages - 1:
                    new_cache, x = fn(params[s], cache[s], x,
                                      positions, page_row, **lora_kw)
                else:
                    new_cache, last = fn(params[s], cache[s], x,
                                         positions, plen, page_row,
                                         **lora_kw)
                caches.append(new_cache)
            return caches, last

        # Same story as the decode chain: prefill chunks dispatch
        # only from the scheduler loop.
        return chain  # stpu: role[scheduler]

    # -- jitted device fns --------------------------------------------------
    def _make_decode_fn(self):
        if self.stages > 1:
            return self._make_staged_decode_chain()
        model = self.model

        # Donate the cache: the caller always replaces self.cache with
        # the result, so XLA updates in place instead of copying the
        # full KV cache every token (no-op on CPU, vital on TPU).
        paged = self.paged

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._pin_cache_out(None))
        def decode(params, cache, cur_token, pos, temps, top_ks,
                   top_ps, rng, page_indices=None, lora=None,
                   adapter_ids=None):
            extra = {'page_indices': page_indices} if paged else {}
            if lora is not None:
                extra.update(lora=lora, adapter_ids=adapter_ids)
            logits, mutated = model.apply(
                {'params': params, 'cache': cache},
                cur_token[:, None], positions=pos[:, None], decode=True,
                mutable=['cache'], **extra)
            # Per-slot temperature/top-k/top-p: greedy where temp==0.
            out = sample_tokens(rng, logits[:, 0], temps, top_ks,
                                top_ps)
            return mutated['cache'], out

        return decode

    def _make_chunk_decode_fn(self):
        """N single-token decode steps in ONE jitted dispatch: the
        whole chunk is a lax.scan whose carry is (cache, token, pos,
        rng). The rng chain is jax.random.split exactly as the
        step-by-step loop performs it, so sampled outputs are
        bit-identical; the host commits tokens afterwards, truncating
        at each slot's limit/eos/stop (post-finish writes are junk the
        next chunk or prefill overwrites before attending — the
        write-before-read contract shared with speculation)."""
        model = self.model
        paged = self.paged
        n = self.decode_chunk

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._pin_cache_out(None, None))
        def chunk_decode(params, cache, cur_token, pos, temps, top_ks,
                         top_ps, rng, page_indices=None, lora=None,
                         adapter_ids=None):
            extra = {'page_indices': page_indices} if paged else {}
            if lora is not None:
                extra.update(lora=lora, adapter_ids=adapter_ids)

            def step(carry, _):
                cache, tok, pos, rng = carry
                logits, mutated = model.apply(
                    {'params': params, 'cache': cache},
                    tok[:, None], positions=pos[:, None], decode=True,
                    mutable=['cache'], **extra)
                rng, sub = jax.random.split(rng)
                out = sample_tokens(sub, logits[:, 0], temps, top_ks,
                                    top_ps)
                return (mutated['cache'], out, pos + 1, rng), out

            (cache, _, _, rng), toks = jax.lax.scan(
                step, (cache, cur_token, pos, rng), None, length=n)
            return cache, toks, rng            # toks: [n, slots]

        return chunk_decode

    def _make_spec_decode_fn(self):
        """Verification step for prompt-lookup speculation: a
        [slots, K+1] chunk ([current, draft_1..draft_K] per row) runs
        through the model's chunked decode path in ONE call (paged:
        write_kv_chunk + paged_chunk_attention; dense:
        chunked_cache_attention) — between 1 and K+1 tokens commit per
        model call. Returns the model's own next-token choice at every
        chunk position; acceptance is computed host-side.

        Sampling stays EXACT: position t's token is sampled from
        p(. | prefix, draft_<t), and the host only commits it while
        every earlier draft matched the model's choice — so each
        committed token was sampled from the true conditional of the
        committed prefix (greedy is the temperature-0 special case).
        """
        if self.stages > 1:
            # The staged chain is shape-polymorphic: a [B, K+1] chunk
            # retraces the per-stage fns at seq=K+1 and the last
            # stage samples the whole chunk, exactly like the
            # single-mesh verify dispatch below.
            return self._make_staged_decode_chain()
        model = self.model
        paged = self.paged
        k = self.spec_k

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._pin_cache_out(None))
        def spec_decode(params, cache, chunk, pos, temps, top_ks,
                        top_ps, rng, page_indices=None, lora=None,
                        adapter_ids=None):
            positions = pos[:, None] + jnp.arange(k + 1)[None, :]
            extra = {'page_indices': page_indices} if paged else {}
            if lora is not None:
                extra.update(lora=lora, adapter_ids=adapter_ids)
            logits, mutated = model.apply(
                {'params': params, 'cache': cache}, chunk,
                positions=positions, decode=True, mutable=['cache'],
                **extra)                                   # [B, K+1, V]
            out = sample_tokens(rng, logits, temps, top_ks, top_ps)
            return mutated['cache'], out

        return spec_decode

    def _draft(self) -> 'np.ndarray':
        """Host-side prompt-lookup drafts [slots, K]: for each active
        slot, the K tokens that followed the most recent earlier
        occurrence of the trailing `spec_ngram` (context = committed
        output ++ pending current token); no match (or inactive) =
        repeat the last token (worst case: 1 commit per step, same as
        plain decode).

        The backward scan is bounded to the trailing `spec_lookback`
        tokens so host-side draft cost per decode round stays O(1) in
        the generation length (unbounded it is O(output_len) per round
        — quadratic overall — on the single scheduler thread)."""
        k, ngram = self.spec_k, self.spec_ngram
        drafts = np.zeros((self.num_slots, k), np.int32)
        for slot in range(self.num_slots):
            if not self.active[slot]:
                continue
            ctx = self.outputs[slot] + [int(self.cur_token[slot])]
            last = ctx[-1]
            drafts[slot, :] = last
            if len(ctx) <= ngram:
                continue
            pattern = ctx[-ngram:]
            floor = max(0, len(ctx) - self.spec_lookback)
            # Most recent strictly-earlier occurrence of the pattern.
            for start in range(len(ctx) - ngram - 1, floor - 1, -1):
                if ctx[start:start + ngram] == pattern:
                    cont = ctx[start + ngram:start + ngram + k]
                    if cont:
                        drafts[slot, :len(cont)] = cont
                        drafts[slot, len(cont):] = cont[-1]
                    break
        return drafts

    def _prefill_fn(self, bucket_len: int):
        """fn(params, cache, slot, prompt[P], plen) -> (cache, next_tok).

        CHUNKED prefill: ONE forward pass over the padded prompt
        that also writes every position's K/V (the model's
        decode-with-seq>1 mode) — not a per-token scan. Dense: runs on
        a batch-1 slice of the slot's cache rows, then scatters the
        rows back. Paged: the cache has no slot dimension — the pass
        runs on the full (donated) pool and writes only the slot's own
        pages via its page-table row; padded-tail writes land in
        allocated-but-masked slots or the trash page. Either way other
        slots are untouched, so prefill interleaves with the shared
        decode loop.
        """
        if bucket_len in self._prefill_fns:
            return self._prefill_fns[bucket_len]
        if self.stages > 1:
            fn = self._staged_prefill_chain(bucket_len, fresh=True)
            self._prefill_fns[bucket_len] = fn
            return fn
        model = self.model
        positions = jnp.arange(bucket_len, dtype=jnp.int32)[None, :]
        if self.paged:

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._pin_cache_out(None))
            def prefill_paged(params, cache, prompt, plen, page_row,
                              lora=None, adapter_ids=None):
                # CHUNKED prefill: the whole (padded) prompt in ONE
                # forward pass; the model writes K/V for every
                # position (write_kv_chunk). Junk past plen lands in
                # allocated-but-masked slots or the trash page.
                # prefill=True: the sequence starts empty, attention
                # stays chunk-local.
                extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                         if lora is not None else {})
                logits, mutated = model.apply(
                    {'params': params, 'cache': cache},
                    prompt[None, :], positions=positions,
                    decode=True, mutable=['cache'],
                    page_indices=page_row, prefill=True, **extra)
                # The continuation samples from the LAST REAL prompt
                # position, not the padded tail.
                last = jax.lax.dynamic_index_in_dim(
                    logits[0].astype(jnp.float32), plen - 1, axis=0,
                    keepdims=False)
                return mutated['cache'], last

            self._prefill_fns[bucket_len] = prefill_paged
            return prefill_paged

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._pin_cache_out(None))
        def prefill(params, cache, slot, prompt, plen, lora=None,
                    adapter_ids=None):
            extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                     if lora is not None else {})
            row = jax.tree.map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0)
                if c.ndim else c, cache)
            row = jax.tree.map(
                lambda c: jnp.zeros_like(c) if c.ndim else c, row)
            # CHUNKED prefill on the batch-1 row (junk K/V past plen is
            # overwritten by later decode steps before the mask exposes
            # it), then scatter the row back. prefill=True: the row is
            # zeroed, so attention stays chunk-local (S x S,
            # flash-eligible) instead of S x max_seq_len scores.
            logits, mutated = model.apply(
                {'params': params, 'cache': row},
                prompt[None, :], positions=positions,
                decode=True, mutable=['cache'], prefill=True, **extra)
            row = mutated['cache']
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), plen - 1, axis=0,
                keepdims=False)
            cache = jax.tree.map(
                lambda big, small:
                jax.lax.dynamic_update_slice_in_dim(big, small, slot,
                                                    axis=0)
                if big.ndim else small, cache, row)
            return cache, last

        self._prefill_fns[bucket_len] = prefill
        return prefill

    def _prefill_suffix_fn(self, bucket_len: int):
        """fn(params, cache, suffix[P], suffix_len, offset, page_row)
        -> (cache, last_logits): chunked prefill of a prompt SUFFIX
        whose first `offset` tokens are already resident in (shared)
        KV pages. prefill=False — the chunk attends the FULL history
        through the page table (the speculative-verify attention
        path), and its writes land only at positions >= offset, i.e.
        never in a shared page."""
        key = ('suffix', bucket_len)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        if self.stages > 1:
            fn = self._staged_prefill_chain(bucket_len, fresh=False)
            self._prefill_fns[key] = fn
            return fn
        model = self.model

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._pin_cache_out(None))
        def prefill_suffix(params, cache, suffix, suffix_len, offset,
                           page_row, lora=None, adapter_ids=None):
            extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                     if lora is not None else {})
            positions = (offset +
                         jnp.arange(bucket_len, dtype=jnp.int32))[None, :]
            logits, mutated = model.apply(
                {'params': params, 'cache': cache},
                suffix[None, :], positions=positions,
                decode=True, mutable=['cache'],
                page_indices=page_row, prefill=False, **extra)
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), suffix_len - 1, axis=0,
                keepdims=False)
            return mutated['cache'], last

        self._prefill_fns[key] = prefill_suffix
        return prefill_suffix

    def _dense_suffix_fn(self, bucket_len: int):
        """fn(params, cache, slot, suffix[P], suffix_len, offset)
        -> (cache, last_logits): the dense-cache analog of
        `_prefill_suffix_fn` for chunked prefill. Runs the chunk on
        the slot's batch-1 cache row WITHOUT zeroing it (earlier
        chunks' K/V are the history), prefill=False so attention
        covers the full row through `offset` + the chunk itself
        (the chunked-cache-attention path speculation uses), then
        scatters the row back. Padded-tail writes land past the real
        suffix and are overwritten before any later step attends them
        (write-before-read)."""
        key = ('dense_suffix', bucket_len)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        model = self.model

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._pin_cache_out(None))
        def dense_suffix(params, cache, slot, suffix, suffix_len,
                         offset, lora=None, adapter_ids=None):
            extra = ({'lora': lora, 'adapter_ids': adapter_ids}
                     if lora is not None else {})
            row = jax.tree.map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1,
                                                       axis=0)
                if c.ndim else c, cache)
            positions = (offset +
                         jnp.arange(bucket_len,
                                    dtype=jnp.int32))[None, :]
            logits, mutated = model.apply(
                {'params': params, 'cache': row},
                suffix[None, :], positions=positions,
                decode=True, mutable=['cache'], prefill=False, **extra)
            row = mutated['cache']
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), suffix_len - 1, axis=0,
                keepdims=False)
            cache = jax.tree.map(
                lambda big, small:
                jax.lax.dynamic_update_slice_in_dim(big, small, slot,
                                                    axis=0)
                if big.ndim else small, cache, row)
            return cache, last

        self._prefill_fns[key] = dense_suffix
        return dense_suffix

    # -- public API ---------------------------------------------------------
    def submit(self, prompt: List[int],
               max_new_tokens: int = 64,
               temperature: Optional[float] = None,
               top_k: int = 0, top_p: float = 1.0,
               stop_token_ids: Optional[List[int]] = None,
               on_token: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               adapter: Optional[str] = None,
               trace_ctx: Optional['tracing.Ctx'] = None
               ) -> 'Future':
        """Queue a request; the Future resolves to the full token list
        (prompt ++ generated). `temperature` overrides the engine
        default per request (0 = greedy); `top_k`/`top_p` filter the
        sampled distribution (0 / 1.0 = off); `stop_token_ids` end
        THIS request on any listed token (in addition to the engine's
        eos_id), with the stop token included in the output.

        `deadline_s` bounds the request's WHOLE life (queue wait +
        decode), in seconds from now: an expired request is reaped
        between decode rounds — whether still queued or mid-decode —
        and its Future raises DeadlineExceededError.

        Raises QueueSaturatedError (shed: the bounded queue is full)
        and EngineDeadError (the scheduler thread died) instead of
        queueing work that cannot be served.

        `adapter` names a LoRA adapter from the engine's adapter
        store (None = base model): the slot decodes with that
        adapter's factors gathered into the shared dispatch, its KV
        pages are keyed per-adapter in the prefix cache, and the
        adapter stays pinned in the device store until the request
        leaves its slot. Unknown names raise AdapterNotFoundError
        here (before queueing).

        `on_token` streams: called once per COMMITTED generated token,
        in order, on the scheduler thread — before the Future resolves
        — so it must be fast and non-blocking (push to a queue; don't
        do I/O). Tokens regenerated after a page-pressure preemption
        are not re-delivered (they became prompt on re-admission).

        `trace_ctx` attaches a distributed-tracing context
        (observability/tracing.py): the scheduler emits queue-wait /
        admission / prefill-chunk / decode-round spans under it. None
        (unsampled, the default) adds zero per-request work."""
        if self._dead.is_set():
            raise EngineDeadError(
                'engine scheduler thread is dead; restart the server')
        if len(prompt) >= self.max_total_len:
            raise ValueError(
                f'prompt len {len(prompt)} >= max_total_len '
                f'{self.max_total_len}')
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f'top_p must be in (0, 1], got {top_p}')
        if top_k < 0:
            raise ValueError(f'top_k must be >= 0, got {top_k}')
        if adapter is not None:
            if self.adapter_store is None:
                raise AdapterNotFoundError(
                    f'adapter {adapter!r} requested but this engine '
                    f'has no adapter store (serve_lm --adapter-dir)')
            # Inventory check only (404 fast); the load happens at
            # admission on the scheduler thread.
            self.adapter_store.resolve(adapter)
        with self._shed_lock:
            if self.max_queue_requests and \
                    self._queue.qsize() + len(self._ready) >= \
                    self.max_queue_requests:
                self.requests_shed += 1
                raise QueueSaturatedError(
                    f'queue full ({self.max_queue_requests} requests '
                    f'waiting); retry later')
            if self.max_queue_tokens and \
                    self._queued_tokens_n + len(prompt) > \
                    self.max_queue_tokens:
                self.requests_shed += 1
                raise QueueSaturatedError(
                    f'queued prompt tokens would exceed '
                    f'{self.max_queue_tokens}; retry later')
            self._queued_tokens_n += len(prompt)
        temp = self.temperature if temperature is None else temperature
        deadline = (time.monotonic() + float(deadline_s)
                    if deadline_s is not None else 0.0)
        fut: Future = Future()
        # `tref` carries (ctx, enqueue perf_counter) so admission can
        # emit the queue-wait span; None for unsampled requests (no
        # clock read). Positional invariants the rest of the
        # scheduler relies on survive: item[0] is the prompt,
        # item[-2] the deadline, item[-1] the future.
        tref = ((trace_ctx, time.perf_counter())
                if trace_ctx is not None else None)
        self._queue.put((list(prompt), int(max_new_tokens),
                         float(temp), int(top_k), float(top_p),
                         frozenset(stop_token_ids or ()), adapter,
                         tref, on_token, deadline, fut))
        return fut

    def cancel(self, futs) -> None:
        """Best-effort cancel of submitted requests (the client hung
        up mid-stream): an active slot finishes NOW with its output so
        far (freeing the slot instead of decoding tokens nobody will
        read); a queued request resolves without running. Thread-safe;
        applied by the scheduler between decode rounds."""
        with self._cancel_lock:
            self._cancel_requests.update(futs)

    def _apply_cancellations(self) -> None:
        with self._cancel_lock:
            if not self._cancel_requests:
                return
            cancels = self._cancel_requests
            self._cancel_requests = set()
        for slot in range(self.num_slots):
            if (self.active[slot] or self.prefilling[slot]) and \
                    self.futures[slot] in cancels:
                self._finish_slot(slot)
        # Requests still sitting in _queue (submitted after the last
        # _admit drain) must be swept too, or a disconnected client's
        # queued request is later admitted and decoded to completion.
        # Drain into _ready first — the same FCFS append _admit does —
        # then one sweep covers both.
        while True:
            try:
                self._ready.append(self._queue.get_nowait())
            except queue.Empty:
                break
        keep: 'collections.deque' = collections.deque()
        while self._ready:
            item = self._ready.popleft()
            if item[-1] in cancels:
                self._queued_tokens_sub(len(item[0]))
                item[-1].set_result(list(item[0]))  # prompt only
            else:
                keep.append(item)
        self._ready = keep

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def kv_cache_bytes(self) -> int:
        """Device bytes of the slot pool's KV cache (paged pools:
        pages + scale arrays; dense: the per-slot rows) — the
        denominator of the quantized-serving memory math
        (skypilot_serving_kv_pool_bytes)."""
        # Metadata-only read (shape/dtype, never buffer contents):
        # safe from scrape threads even though the cache is donated.
        return int(sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(self.cache)))  # stpu: ignore[SKY008]

    def kv_cache_bytes_per_device(self) -> int:
        """Bytes of the KV cache resident on ONE device: sharded pool
        values count a single shard, replicated leaves (scale pages,
        bookkeeping) count in full. Equals kv_cache_bytes() on a
        single device; ~1/mesh_devices of it when the kv-heads axis
        shards — the per-chip HBM figure --kv-pool-bytes budgets
        (skypilot_serving_kv_pool_bytes_per_device)."""
        # Staged engines: a chip belongs to exactly ONE stage, so the
        # per-chip figure is the WIDEST stage's per-device sum (the
        # layer remainder is front-loaded; other stages hold less).
        trees = self.cache if self.stages > 1 else [self.cache]  # stpu: ignore[SKY008]
        per_stage = []
        for tree in trees:
            total = 0
            # Metadata-only read, same story as kv_cache_bytes.
            for leaf in jax.tree_util.tree_leaves(tree):  # stpu: ignore[SKY008]
                sharding = getattr(leaf, 'sharding', None)
                shape = (sharding.shard_shape(leaf.shape)
                         if sharding is not None else leaf.shape)
                n = 1
                for d in shape:
                    n *= int(d)
                total += n * jnp.dtype(leaf.dtype).itemsize
            per_stage.append(total)
        return int(max(per_stage))

    def stage_pool_stats(self) -> List[Dict[str, Any]]:
        """Per-stage view of the staged KV pool for /stats: ONE
        shared allocator drives the whole stage chain, so every
        stage stores the SAME page indices (counts match), but each
        stage's pool materializes only its own [lo, hi) layer range
        — bytes track the layer split. Empty when stages == 1."""
        if self.stages <= 1:
            return []
        out: List[Dict[str, Any]] = []
        for s, (lo, hi) in enumerate(self._stage_ranges):
            total = 0
            # Metadata-only read, same story as kv_cache_bytes.
            for leaf in jax.tree_util.tree_leaves(self.cache[s]):  # stpu: ignore[SKY008]
                sharding = getattr(leaf, 'sharding', None)
                shape = (sharding.shard_shape(leaf.shape)
                         if sharding is not None else leaf.shape)
                n = 1
                for d in shape:
                    n *= int(d)
                total += n * jnp.dtype(leaf.dtype).itemsize
            out.append({'stage': s, 'layers': [lo, hi],
                        'pages': self.total_pages,
                        'pool_bytes_per_device': int(total)})
        return out

    def attention_impl(self) -> str:
        """Resolved paged-attention implementation this engine's traced
        forwards dispatch to (ops/pallas_paged.resolve_impl under the
        current process-wide dispatch state), or 'dense' when the
        engine runs the dense per-slot cache — no paged kernel in
        play. Surfaced via the attention_impl_info gauge and /stats."""
        if not self.paged:
            return 'dense'
        from skypilot_tpu.ops import pallas_paged
        return pallas_paged.resolve_impl(
            'auto', quantized=self.kv_dtype == 'int8')

    def attention_bytes_per_token(self) -> Dict[str, Any]:
        """Analytic HBM bytes one decode step moves per generated
        token at the CURRENT decode batch — the serve_bench roofline
        denominator (ops/pallas_paged.bytes_per_token_model, fed the
        engine's real page geometry, dtypes and adapter store). Dense
        engines model their full-cache walk with no dequant term."""
        from skypilot_tpu.ops import pallas_paged
        cfg = self.model.config
        if self._weight_bytes is None:
            from skypilot_tpu.inference import quant as quant_lib
            # Staged engines stream only ONE stage's weights per chip
            # per token: the widest stage bounds the roofline.
            self._weight_bytes = (
                max(quant_lib.weight_num_bytes(p) for p in self.params)
                if self.stages > 1
                else quant_lib.weight_num_bytes(self.params))
        lora_bytes = 0
        if self.adapter_store is not None:
            rank = int(getattr(self.adapter_store, '_rank', 0) or 0)
            targets = tuple(
                getattr(self.adapter_store, '_targets', ()) or ())
            if rank > 0 and targets:
                lora_bytes = lora_lib.adapter_num_bytes(cfg, rank,
                                                        targets)
        quantized = self.paged and self.kv_dtype == 'int8'
        elem = (1 if quantized else
                jnp.dtype(getattr(cfg, 'dtype', jnp.bfloat16)).itemsize)
        if self.paged:
            page_size, pages_per_seq = self.page_size, self.pages_per_seq
        else:
            page_size, pages_per_seq = 1, self.max_total_len
        # Per-stage layer split: a chip walks only its stage's layers'
        # KV pages (ceil — the widest stage, matching the weight term).
        num_layers = (-(-cfg.num_layers // self.stages)
                      if self.stages > 1 else cfg.num_layers)
        return pallas_paged.bytes_per_token_model(
            num_layers=num_layers,
            num_kv_heads=getattr(cfg, 'num_kv_heads', cfg.num_heads),
            num_q_heads=cfg.num_heads,
            head_dim=cfg.head_dim,
            page_size=page_size,
            pages_per_seq=pages_per_seq,
            kv_elem_bytes=elem,
            quantized=quantized,
            impl=self.attention_impl(),
            weight_bytes=self._weight_bytes,
            batch=max(int(self.active.sum()), 1),
            lora_bytes_per_row=lora_bytes)

    def update_metric_gauges(self) -> None:
        """Refresh the snapshot-style Prometheus gauges from live
        engine state. Called by the scrape handlers (/metrics and
        /stats) — reads race the scheduler thread harmlessly (numpy
        scalar reads; a stale value is one round old at worst)."""
        self.metrics.queue_depth.set(self._queue.qsize() +
                                     len(self._ready))
        self.metrics.active_slots.set(int(self.active.sum()))
        self.metrics.num_slots.set(self.num_slots)
        self.metrics.prefill_backlog.set(self.prefill_backlog_tokens())
        self.metrics.kv_pool_bytes.set(self.kv_cache_bytes())
        self.metrics.kv_pool_bytes_per_device.set(
            self.kv_cache_bytes_per_device())
        if self.paged:
            free = int(self.allocator.free_pages)
            self.metrics.pages_free.set(free)
            self.metrics.pages_used.set(self.total_pages - free)
        if self.kv_restore_lookups:
            self.metrics.kv_restore_hit_ratio.set(
                self.kv_restore_hits / self.kv_restore_lookups)
        self.metrics.pipeline_stages.set(self.stages)
        self.metrics.prefill_bubble_fraction.set(self._prefill_bubble)
        self.metrics.set_attention_info(self.attention_impl(),
                                        self.kv_dtype)
        self.metrics.attention_bytes_per_token.set(
            self.attention_bytes_per_token()['total_bytes_per_token'])

    # -- KV page transfer + tiered cache ------------------------------------
    def run_on_scheduler(self, fn, timeout: float = 120.0):  # stpu: hop[scheduler]
        """Run `fn()` on the scheduler thread between rounds and
        return its result (exceptions re-raise here). The ONLY safe
        way to touch `self.cache` from another thread: every dispatch
        donates the cache buffer, so a concurrent gather/scatter from
        an HTTP thread would race the dispatch that consumes it.
        Calls made ON the scheduler thread run inline (control ops
        compose)."""
        if threading.current_thread() is self._thread:
            return fn()
        if self._dead.is_set():
            raise EngineDeadError(
                'engine scheduler thread is dead; restart the server')
        fut: Future = Future()
        self._control.put((fn, fut))
        return fut.result(timeout=timeout)

    def _run_control_ops(self) -> bool:
        """Drain pending control operations (start of each scheduler
        iteration). An op's failure resolves only ITS caller's future
        — unless it consumed the donated cache, which is the same
        unrecoverable condition as a failed dispatch and takes the
        full reset path."""
        ran = False
        while True:
            try:
                fn, fut = self._control.get_nowait()
            except queue.Empty:
                return ran
            ran = True
            try:
                fut.set_result(fn())
            except Exception as e:  # pylint: disable=broad-except
                fut.set_exception(e)
                if self._cache_lost():
                    raise

    def _gather_page_blobs(self, pages: List[int]
                           ) -> Dict[str, 'np.ndarray']:
        """Exact device bytes of physical pages `pages`, as
        {cache-leaf path: page-major host array} — the export side of
        handoff and spill. int8 pools gather int8 payload AND the f32
        scale rows; no dequantization anywhere (bit-identical round
        trip). Sharded pools gather per shard — the eager row gather
        runs on each device's own heads slice and the device_get
        assembles GLOBAL rows (the one place the export path pays a
        cross-device fetch; the decode path never does). Scheduler
        thread only."""
        from skypilot_tpu.ops import paged_attention as paged_ops
        idx = jnp.asarray(pages, jnp.int32)
        # Staged engines: the per-stage trees use ABSOLUTE layer
        # names, so the union of their leaf paths IS the single-mesh
        # path set — the wire format is mesh-agnostic across stage
        # splits (stage-S exports import into stage-1 and back).
        trees = self.cache if self.stages > 1 else [self.cache]
        flat = []
        for tree in trees:
            flat.extend(jax.tree_util.tree_flatten_with_path(tree)[0])
        gathered = [paged_ops.gather_page_rows(leaf, idx)
                    for _path, leaf in flat]
        fetched = jax.device_get(gathered)
        return {jax.tree_util.keystr(path): np.asarray(arr)
                for (path, _), arr in zip(flat, fetched)}

    def _scatter_fn(self, m: int, stage: Optional[int] = None):
        key = m if stage is None else (m, stage)
        if key not in self._scatter_fns:
            from skypilot_tpu.ops import paged_attention as paged_ops

            @functools.partial(jax.jit, donate_argnums=(0,),
                               **self._pin_cache_out(stage=stage))
            def scatter(cache, idx, rows):
                return jax.tree.map(
                    lambda a, r: paged_ops.scatter_page_rows(a, idx,
                                                             r),
                    cache, rows)

            self._scatter_fns[key] = scatter
        return self._scatter_fns[key]

    def _scatter_page_blobs(self, pages: List[int],
                            blobs: Dict[str, 'np.ndarray']) -> None:
        """Write page-major host blobs into physical pages `pages`
        (import/restore). Chain lengths pad to a power of two so the
        jitted donating scatter compiles a log2 ladder, not one
        executable per length; pad rows target physical page 0 — the
        trash page, junk over junk. Staged engines route each leaf to
        its owning stage's pool (absolute layer names make the union
        of the stage trees the full single-mesh leaf set) and scatter
        per stage with that stage's donating pinned dispatch.
        Scheduler thread only."""
        staged = self.stages > 1
        trees = self.cache if staged else [self.cache]
        per_stage = []
        all_paths: List[str] = []
        for tree in trees:
            flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
            paths = [jax.tree_util.keystr(p) for p, _ in flat]
            per_stage.append((flat, treedef, paths))
            all_paths.extend(paths)
        if sorted(all_paths) != sorted(blobs):
            raise ValueError(
                f'KV chain leaves do not match this engine\'s cache '
                f'layout (chain: {sorted(blobs)[:3]}..., cache: '
                f'{sorted(all_paths)[:3]}...)')
        n = len(pages)
        m = 1
        while m < n:
            m *= 2
        idx = np.zeros((m,), np.int32)
        idx[:n] = pages
        new_trees = []
        for s, (flat, treedef, paths) in enumerate(per_stage):
            rows = []
            for (_p, leaf), path in zip(flat, paths):
                arr = np.asarray(blobs[path])
                if leaf.ndim == 4:
                    want = (n, leaf.shape[0], leaf.shape[2],
                            leaf.shape[3])
                else:
                    want = (n, leaf.shape[1])
                if tuple(arr.shape) != want or \
                        arr.dtype != np.dtype(leaf.dtype):
                    raise ValueError(
                        f'KV chain leaf {path} is '
                        f'{arr.dtype}{arr.shape}, pool expects '
                        f'{np.dtype(leaf.dtype)}{want}')
                if m != n:
                    arr = np.concatenate(
                        [arr, np.zeros((m - n,) + arr.shape[1:],
                                       arr.dtype)], axis=0)
                rows.append(arr)
            rows_tree = jax.tree_util.tree_unflatten(treedef, rows)
            fn = self._scatter_fn(m, s if staged else None)
            new_trees.append(fn(trees[s], jnp.asarray(idx),
                                rows_tree))
        self.cache = new_trees if staged else new_trees[0]

    def export_chain(self, tokens: List[int],
                     adapter: Optional[str] = None
                     ) -> Optional[bytes]:
        """Serialize the prompt's cached full-page KV chain (payload
        + scales + adapter-salted chain keys + geometry) for handoff
        to another replica. Returns packed bytes covering the longest
        cached chain prefix, or None when nothing is cached (or
        prefix caching is off). Thread-safe: hops onto the scheduler
        thread; the chain is reference-pinned during the gather."""
        if not self.prefix_caching:
            return None
        toks = [int(t) for t in tokens]

        def op():
            from skypilot_tpu.inference import kv_transfer
            cache = self.prefix_cache
            salt = b''
            if adapter is not None:
                if self.adapter_store is None:
                    raise AdapterNotFoundError(
                        f'adapter {adapter!r} requested for export '
                        f'but this engine has no adapter store')
                salt = self.adapter_store.cache_salt(adapter)
            keys = PrefixCache.chain_keys(toks, self.page_size,
                                          salt=salt)
            if not keys:
                return None
            pages = cache.lookup_acquire(keys, record=False)
            try:
                if not pages:
                    return None
                blobs = self._gather_page_blobs(pages)
            finally:
                cache.release(pages)
            # kv-head geometry rides the header (PR 15): blobs hold
            # GLOBAL page rows — _gather_page_blobs's device_get
            # assembles the shards — so a pool sharded a DIFFERENT
            # number of ways (or not at all) can validate and
            # rescatter them; the importing engine's own
            # out_shardings re-split the heads axis on its mesh.
            cfg = self.model.config
            meta = {'kind': 'kv_chain',
                    'kv_dtype': self.kv_dtype,
                    'page_size': self.page_size,
                    'num_kv_heads': int(getattr(cfg, 'num_kv_heads',
                                                0) or 0),
                    'head_dim': int(getattr(cfg, 'head_dim', 0) or 0),
                    'num_layers': int(getattr(cfg, 'num_layers',
                                              0) or 0),
                    'keys': [k.hex() for k in keys[:len(pages)]],
                    'salt': salt.hex()}
            packed = kv_transfer.pack_pages(blobs, meta)
            self.flight.record('handoff_export', pages=len(pages),
                               bytes=len(packed))
            return packed

        return self.run_on_scheduler(op)

    def import_chain(self, data: bytes) -> Dict[str, int]:
        """Scatter a packed page chain into this pool and register it
        in the prefix cache: the next submit of the same prompt (same
        adapter salt) admits against the imported pages instead of
        re-running prefill. Pages whose keys are already cached are
        skipped; pages that cannot fit even after spill-eviction are
        dropped (chain order — a dropped page also drops its
        suffix's usefulness, counted for the caller). Raises
        ValueError on any geometry/dtype mismatch. Thread-safe."""
        if not self.prefix_caching:
            raise ValueError(
                'import_chain needs the paged engine with prefix '
                'caching enabled')

        def op():
            from skypilot_tpu.inference import kv_transfer
            meta, blobs = kv_transfer.unpack_pages(data)
            if meta.get('kind') != 'kv_chain':
                raise ValueError('not a KV chain payload')
            if meta.get('kv_dtype') != self.kv_dtype:
                raise ValueError(
                    f'kv_dtype mismatch: chain is '
                    f'{meta.get("kv_dtype")!r}, pool is '
                    f'{self.kv_dtype!r}')
            if int(meta.get('page_size', 0)) != self.page_size:
                raise ValueError(
                    f'page_size mismatch: chain is '
                    f'{meta.get("page_size")}, pool is '
                    f'{self.page_size}')
            # kv-head geometry (headers from PR-13 exporters lack it;
            # leaf-shape validation in _scatter_page_blobs still
            # catches those mismatches). Mesh SIZE is deliberately
            # not compared: chains carry global rows, so a tensor-2
            # export imports into a tensor-1 pool and back.
            cfg = self.model.config
            for field, want in (
                    ('num_kv_heads',
                     int(getattr(cfg, 'num_kv_heads', 0) or 0)),
                    ('head_dim',
                     int(getattr(cfg, 'head_dim', 0) or 0)),
                    # Layer count (PR 19): blobs carry one row per
                    # layer, so a layer-count mismatch would scatter
                    # rows into the wrong layers' pools. Stage SPLIT
                    # is deliberately not compared — blobs are keyed
                    # by absolute layer names, mesh-agnostic.
                    ('num_layers',
                     int(getattr(cfg, 'num_layers', 0) or 0))):
                got = meta.get(field)
                if got is not None and int(got) and want and \
                        int(got) != want:
                    raise ValueError(
                        f'{field} mismatch: chain is {got}, pool '
                        f'is {want}')
            keys = [bytes.fromhex(k) for k in meta.get('keys', [])]
            if len(keys) != int(meta.get('n_pages', -1)):
                raise ValueError('chain key count != page count')
            cache = self.prefix_cache
            todo = [(i, key) for i, key in enumerate(keys)
                    if key not in cache.by_key]
            already = len(keys) - len(todo)
            if todo:
                cache.evict_into(self.allocator, len(todo))
            fit = todo[:self.allocator.free_pages]
            dropped = len(todo) - len(fit)
            if fit:
                pages = self.allocator.allocate(len(fit))
                rows = {path: arr[[i for i, _ in fit]]
                        for path, arr in blobs.items()}
                try:
                    self._scatter_page_blobs(pages, rows)
                except Exception:
                    self.allocator.release(pages)
                    raise
                for (_i, key), page in zip(fit, pages):
                    cache.insert(key, page)
            self.flight.record('kv_import', pages=len(keys),
                               imported=len(fit),
                               already_cached=already,
                               dropped=dropped)
            return {'pages': len(keys), 'imported': len(fit),
                    'already_cached': already, 'dropped': dropped}

        return self.run_on_scheduler(op)

    def _evacuate_slot(self, slot: int, reason: str) -> Dict[str, Any]:
        """Evacuate ONE occupied slot (scheduler thread only): pack
        the committed-token KV chain, tear the slot down, and resolve
        its future with SessionMigratedError carrying everything a
        peer needs to finish the session. Mirrors _fail_slot's
        teardown order, with two migration twists: (1) the chain is
        gathered from the slot's LIVE page table (prefix+generated,
        not just the prompt chain export_chain covers); (2) before
        release, `slot_keys` is rewritten to the FULL committed chain
        so promote=True parks every exported page in the local prefix
        cache too — a failed ship falls back to warm local pages, not
        a cold replay. Mid-prefill slots ship no payload and never
        promote (pages past the frontier are unwritten junk)."""
        committed = [int(t) for t in self.outputs[slot]]
        adapter = self.slot_adapter_name[slot]
        was_prefilling = bool(self.prefilling[slot])
        payload = None
        n_chain = 0
        if self.paged and self.prefix_cache is not None and \
                not was_prefilling:
            salt = b''
            if adapter is not None and self.adapter_store is not None:
                salt = self.adapter_store.cache_salt(adapter)
            keys = PrefixCache.chain_keys(committed, self.page_size,
                                          salt=salt)
            if keys:
                try:
                    from skypilot_tpu.inference import kv_transfer
                    phys = [int(p) for p in
                            self.page_table[slot, :len(keys)]]
                    blobs = self._gather_page_blobs(phys)
                    cfg = self.model.config
                    meta = {'kind': 'kv_chain',
                            'kv_dtype': self.kv_dtype,
                            'page_size': self.page_size,
                            'num_kv_heads': int(getattr(
                                cfg, 'num_kv_heads', 0) or 0),
                            'head_dim': int(getattr(
                                cfg, 'head_dim', 0) or 0),
                            'num_layers': int(getattr(
                                cfg, 'num_layers', 0) or 0),
                            'keys': [k.hex() for k in keys],
                            'salt': salt.hex()}
                    payload = kv_transfer.pack_pages(blobs, meta)
                    n_chain = len(keys)
                except Exception:  # pylint: disable=broad-except
                    payload = None  # ship nothing; peer re-prefills
                # Full-chain promotion on teardown (see docstring).
                self.slot_keys[slot] = keys
        deadline = float(self.deadlines[slot])
        record = {
            'reason': reason,
            'tokens': committed,
            'prompt_len': int(self.prompt_len[slot]),
            'limit': int(self.limits[slot]),
            'temperature': float(self.temps[slot]),
            'top_k': int(self.top_ks[slot]),
            'top_p': float(self.top_ps[slot]),
            'stop_token_ids': sorted(self.stop_ids[slot]),
            'adapter': adapter,
            'deadline_s': (max(deadline - time.monotonic(), 0.5)
                           if deadline else 0.0),
            'payload': payload,
            'pages': n_chain,
        }
        fut = self.futures[slot]
        self.futures[slot] = None
        self.active[slot] = False
        self.on_tokens[slot] = None
        self.deadlines[slot] = 0.0
        self._slot_ctx[slot] = None
        self._release_adapter(slot)
        if was_prefilling:
            self.prefilling[slot] = False
            try:
                self._prefill_order.remove(slot)
            except ValueError:
                pass
        if self.paged:
            self._release_slot_pages(slot,
                                     promote=not was_prefilling)
        self.sessions_evacuated += 1
        if payload is not None:
            self.chains_evacuated += 1
        self.flight.record('evacuate', slot=slot, reason=reason,
                           pages=n_chain,
                           bytes=len(payload) if payload else 0)
        if fut is not None:
            fut.set_exception(SessionMigratedError(record))
        return record

    def _evacuate_queued(self, reason: str) -> int:
        """Fail every queued (not-yet-admitted) request with a
        payload-less SessionMigratedError: nothing should sit waiting
        on a dying replica when its caller can resubmit elsewhere
        immediately. Scheduler thread only."""
        while True:
            try:
                self._ready.append(self._queue.get_nowait())
            except queue.Empty:
                break
        n = 0
        while self._ready:
            (prompt, max_new, temp, top_k, top_p, stops, _adapter,
             _tref, _on_token, deadline, fut) = self._ready.popleft()
            self._queued_tokens_sub(len(prompt))
            record = {
                'reason': reason,
                'tokens': [int(t) for t in prompt],
                'prompt_len': len(prompt),
                'limit': min(len(prompt) + int(max_new),
                             self.max_total_len),
                'temperature': float(temp),
                'top_k': int(top_k),
                'top_p': float(top_p),
                'stop_token_ids': sorted(stops),
                'adapter': _adapter,
                'deadline_s': (max(deadline - time.monotonic(), 0.5)
                               if deadline else 0.0),
                'payload': None,
                'pages': 0,
            }
            fut.set_exception(SessionMigratedError(record))
            n += 1
        return n

    def evacuate_chains(self, max_sessions: Optional[int] = None,
                        reason: str = 'drain') -> Dict[str, int]:
        """Evacuate active sessions for live migration (drain,
        preemption notice, or rebalance): each occupied slot's
        committed tokens + packed KV chain come back to its waiting
        HTTP thread as a SessionMigratedError record, and the pages
        stay promoted in the LOCAL prefix cache as the warm fallback.
        `max_sessions=None` evacuates everything INCLUDING the queue
        (full drain); a bounded count (rebalance) takes the
        deepest-chain sessions first — most recompute saved per
        migration — and leaves the queue alone. Thread-safe: hops
        onto the scheduler thread. Returns
        {'evacuated', 'chains', 'queued'}."""

        def op():
            evacuated = 0
            chains = 0
            limit_n = (self.num_slots if max_sessions is None
                       else max(int(max_sessions), 0))
            # Deepest committed sequence first: those chains cost the
            # most to recompute, so under a bounded budget they are
            # the ones worth shipping.
            order = sorted(
                (s for s in range(self.num_slots)
                 if self.active[s] or self.prefilling[s]),
                key=lambda s: -len(self.outputs[s]))
            for slot in order:
                if evacuated >= limit_n:
                    break
                rec = self._evacuate_slot(slot, reason)
                evacuated += 1
                if rec.get('payload') is not None:
                    chains += 1
            queued = (self._evacuate_queued(reason)
                      if max_sessions is None else 0)
            return {'evacuated': evacuated, 'chains': chains,
                    'queued': queued}

        return self.run_on_scheduler(op)

    def _restore_from_spill(self, keys: List[bytes],
                            shared: List[int]) -> None:
        """Extend the device-resident chain prefix from the spill
        tier, in place: for each key past the cached prefix (in chain
        order, stopping at the first miss), allocate a page, scatter
        the spilled bytes back, and acquire it exactly like a
        resident hit. Restored pages are bit-identical to the
        original compute — greedy continuations cannot tell."""
        from skypilot_tpu.inference import kv_transfer
        cache = self.prefix_cache
        # Restore only what can actually land: free pages plus the
        # evictable LRU. Fetching a chain the pool cannot hold wastes
        # host DMA AND churns the tier's own LRU for nothing.
        budget = self.allocator.free_pages + len(cache.lru)
        found_blobs = []
        found_keys = []
        for key in keys[len(shared):]:
            if len(found_blobs) >= budget:
                break
            self.kv_restore_lookups += 1
            blob = self.spill_tier.get(key)
            if blob is None:
                break
            self.kv_restore_hits += 1
            found_blobs.append(blob)
            found_keys.append(key)
        if not found_blobs:
            return
        cache.evict_into(self.allocator, len(found_blobs))
        n_fit = min(len(found_blobs), self.allocator.free_pages)
        if n_fit <= 0:
            return
        pages = self.allocator.allocate(n_fit)
        try:
            self._scatter_page_blobs(
                pages, kv_transfer.join_pages(found_blobs[:n_fit]))
        except Exception:
            self.allocator.release(pages)
            raise
        for key, page in zip(found_keys[:n_fit], pages):
            cache.acquire_page(key, page)
        shared.extend(pages)
        self.kv_restored_pages += n_fit
        self.metrics.kv_restore_pages.inc(n_fit)
        self.flight.record('restore', pages=n_fit)

    # -- scheduler loop -----------------------------------------------------
    def _loop(self) -> None:
        """Run iterations until stopped. Crash-only: if the thread is
        about to die for any reason other than stop() — including a
        non-Exception like an injected SystemExit — it first flips the
        dead flag and fails every pending future, so clients see
        EngineDeadError immediately instead of hanging on a silently
        absent scheduler (and /readyz reports 503)."""
        try:
            while not self._stop.is_set():
                try:
                    self._iterate()
                    self._soft_errors = 0
                except Exception as e:  # pylint: disable=broad-except
                    self._recover_from_error(e)
        finally:
            if not self._stop.is_set():
                self._dead.set()
                self.flight.record('death')
                self.flight.snapshot('death')
                died = EngineDeadError('engine scheduler thread died')
                for slot in range(self.num_slots):
                    fut = self.futures[slot]
                    self.futures[slot] = None
                    self.active[slot] = False
                    self.prefilling[slot] = False
                    self.on_tokens[slot] = None
                    self._release_adapter(slot)
                    if fut is not None and not fut.done():
                        fut.set_exception(died)
                self._fail_all_pending(died)
                while not self._control.empty():
                    try:
                        _fn, cfut = self._control.get_nowait()
                        cfut.set_exception(died)
                    except queue.Empty:
                        break

    def _iterate(self) -> None:
        """One iteration = admit (host-only) -> apply cancellations ->
        reap expired deadlines -> up to `prefill_budget` tokens of
        chunked prefill -> one decode round for the active slots. Long
        prompts therefore interleave with decoding instead of stalling
        it; with pipelining the decode round's host commit overlaps
        the NEXT round's device compute."""
        progressed = self._run_control_ops()
        progressed = self._admit() or progressed
        self._apply_cancellations()
        self._reap_deadlines()
        if self._prefill_order:
            self._prefill_work()
            progressed = True
        if self.active.any() or self._inflight is not None or \
                any(f is not None for f in self._group_inflight):
            t_step = time.perf_counter()
            committed0 = self.tokens_committed
            self._decode_step()
            dt_step = time.perf_counter() - t_step
            self.metrics.decode_step_seconds.observe(dt_step)
            self.flight.record(
                'round_commit',
                tokens=self.tokens_committed - committed0,
                active=int(self.active.sum()))
            if tracing.enabled():
                self._trace_decode_round(dt_step)
            progressed = True
        if not progressed and self._queue.empty() and \
                not self._ready:
            # Idle: block briefly for the next request. The
            # item goes straight into _ready — a get+put-back
            # would rotate the queue head to the TAIL,
            # inverting FCFS admission order.
            try:
                self._ready.append(self._queue.get(timeout=0.05))
            except queue.Empty:
                pass

    def _cache_lost(self) -> bool:
        """True when the donated KV cache buffer is gone (the device
        execution consumed it before failing): every slot's history is
        unrecoverable and only a full reset can continue. False means
        the exception fired BEFORE any device work touched the cache —
        state is consistent and serving can continue."""
        try:
            for leaf in jax.tree_util.tree_leaves(self.cache):
                deleted = getattr(leaf, 'is_deleted', None)
                if deleted is not None and deleted():
                    return True
            return False
        except Exception:  # pylint: disable=broad-except
            return True  # can't even inspect it: treat as lost

    def _recover_from_error(self, e: Exception) -> None:
        """Crash-only error containment, two tiers:

        CACHE INTACT (e.g. an injected fault or host-side error raised
        before the device dispatch): state is consistent — log, count,
        keep serving every slot; nothing is failed. A short fuse
        escalates repeated soft errors so a deterministic pre-dispatch
        failure cannot spin the loop forever.

        CACHE LOST (the donated buffer died inside the device call):
        fail the in-flight and queued requests loudly, reset the slots
        AND the cache, keep serving (the restart is counted in
        engine_restarts / skypilot_serving_engine_restarts_total)."""
        import traceback
        traceback.print_exc()
        self._soft_errors += 1
        victims = [s for s in range(self.num_slots)
                   if self.active[s] or self.prefilling[s]]
        self.flight.record('soft_error', error=type(e).__name__,
                           message=str(e)[:200],
                           strikes=self._soft_errors, slots=victims)
        if not self._cache_lost() and self._soft_errors < 3:
            print(f'engine {self.engine_id}: transient scheduler error '
                  f'({type(e).__name__}: {e}); state intact, '
                  f'continuing', flush=True)
            return
        self.flight.record('reset', error=type(e).__name__,
                           strikes=self._soft_errors, slots=victims,
                           restarts=self.engine_restarts + 1)
        self.flight.snapshot('reset')
        self.engine_restarts += 1
        self.metrics.engine_restarts.inc()
        self._soft_errors = 0
        self._inflight = None
        self._group_inflight = [None] * self.stages
        try:
            self.cache = self._fresh_cache()
        except Exception:  # pylint: disable=broad-except
            traceback.print_exc()  # device truly gone
        for slot in range(self.num_slots):
            fut = self.futures[slot]
            self.futures[slot] = None
            self.active[slot] = False
            self.prefilling[slot] = False
            self.on_tokens[slot] = None
            self._slot_ctx[slot] = None
            self._release_adapter(slot)
            if fut is not None:
                fut.set_exception(e)
        self._prefill_order.clear()
        self.prefill_frontier[:] = 0
        self.prompt_len[:] = 0
        self.pos[:] = 0
        self.cur_token[:] = 0
        self.temps[:] = 0
        self.top_ks[:] = 0
        self.top_ps[:] = 1.0
        self.deadlines[:] = 0.0
        self._fail_all_pending(e)

    def _fail_all_pending(self, e: Exception) -> None:
        """Resolve every queued (not-yet-admitted) future with `e`."""
        while self._ready:
            prompt, *_rest, fut = self._ready.popleft()
            self._queued_tokens_sub(len(prompt))
            fut.set_exception(e)
        while not self._queue.empty():
            try:
                prompt, *_rest, fut = self._queue.get_nowait()
                self._queued_tokens_sub(len(prompt))
                fut.set_exception(e)
            except queue.Empty:
                break

    # -- deadlines / health / admission control -----------------------------
    def _queued_tokens_sub(self, n: int) -> None:
        with self._shed_lock:
            self._queued_tokens_n -= n

    def _queued_tokens_add(self, n: int) -> None:
        with self._shed_lock:
            self._queued_tokens_n += n

    def queued_requests(self) -> int:
        return self._queue.qsize() + len(self._ready)

    def queued_tokens(self) -> int:
        with self._shed_lock:
            return self._queued_tokens_n

    def healthy(self) -> bool:
        """Scheduler thread alive and processing (the /readyz
        signal)."""
        return not self._dead.is_set() and self._thread.is_alive()

    def saturated(self) -> bool:
        """Admission control would shed an (average-sized) request
        right now — surfaced by /readyz so load balancers steer
        traffic away BEFORE clients start eating 429s."""
        if self.max_queue_requests and \
                self.queued_requests() >= self.max_queue_requests:
            return True
        if self.max_queue_tokens and \
                self.queued_tokens() >= self.max_queue_tokens:
            return True
        return False

    def _release_adapter(self, slot: int) -> None:
        """Unpin the slot's adapter (if any) in the device store and
        account its committed tokens. Idempotent: the slot's adapter
        id is cleared on the first call."""
        aid = int(self.slot_adapter[slot])
        if not aid:
            return
        self.slot_adapter[slot] = 0
        self.slot_adapter_name[slot] = None
        if self.adapter_store is not None:
            n_gen = max(len(self.outputs[slot]) -
                        int(self.prompt_len[slot]), 0)
            self.adapter_store.release(aid, tokens=n_gen)

    def _fail_slot(self, slot: int, e: Exception) -> None:
        """Fail ONE slot's request (crash-only isolation): release its
        resources, resolve its future with `e`, keep every other slot
        running. Mid-prefill pages are never promoted (half-written)."""
        fut = self.futures[slot]
        self.futures[slot] = None
        self.active[slot] = False
        self.on_tokens[slot] = None
        self.deadlines[slot] = 0.0
        self._slot_ctx[slot] = None
        self._release_adapter(slot)
        if self.prefilling[slot]:
            self.prefilling[slot] = False
            try:
                self._prefill_order.remove(slot)
            except ValueError:
                pass
        if self.paged:
            self._release_slot_pages(slot, promote=False)
        if fut is not None:
            fut.set_exception(e)

    def _reap_deadlines(self) -> None:
        """Fail every expired request — queued or mid-decode — with
        DeadlineExceededError. Runs between rounds on the scheduler
        thread, so a reaped slot frees its pages before the next
        dispatch and an abandoned request never decodes to its limit."""
        now = time.monotonic()
        for slot in range(self.num_slots):
            dl = float(self.deadlines[slot])
            if dl and now > dl and (self.active[slot] or
                                    self.prefilling[slot]):
                self.deadline_exceeded += 1
                self._fail_slot(slot, DeadlineExceededError(
                    f'request deadline exceeded after '
                    f'{len(self.outputs[slot]) - int(self.prompt_len[slot])} '
                    f'generated tokens'))
        if not self._ready:
            return
        keep: 'collections.deque' = collections.deque()
        while self._ready:
            item = self._ready.popleft()
            deadline = item[-2]
            if deadline and now > deadline:
                self.deadline_exceeded += 1
                self._queued_tokens_sub(len(item[0]))
                item[-1].set_exception(DeadlineExceededError(
                    'request deadline exceeded while queued'))
            else:
                keep.append(item)
        self._ready = keep

    def _occupied(self) -> 'np.ndarray':
        return self.active | self.prefilling

    def _admit(self) -> bool:
        """Drain ready requests into free slots: prefix-cache lookup +
        page allocation + slot bookkeeping only — NO device work. The
        prompt suffix is prefilled by `_prefill_work` (chunked, under
        the token budget), which flips the slot PREFILLING -> active.
        """
        admitted = False
        while True:
            try:
                self._ready.append(self._queue.get_nowait())
            except queue.Empty:
                break
        while self._ready and not self._occupied().all():
            (prompt, max_new, temp, top_k, top_p, stops, adapter,
             tref, on_token, deadline, fut) = self._ready.popleft()
            t_adm = time.perf_counter() if tref is not None else 0.0
            self._queued_tokens_sub(len(prompt))
            if deadline and time.monotonic() > deadline:
                # Expired while queued: prefilling it would only delay
                # live requests further.
                self.deadline_exceeded += 1
                fut.set_exception(DeadlineExceededError(
                    'request deadline exceeded while queued'))
                continue
            if max_new <= 0:
                fut.set_result(list(prompt))  # nothing to generate
                continue
            slot = int(np.argmin(self._occupied()))  # first free slot
            # Adapter resolution BEFORE page work: the store pins
            # (refcounts) the adapter for this slot's lifetime and
            # the prefix-cache keys below are salted with it.
            aid = 0
            salt = b''
            if adapter is not None:
                try:
                    aid = self.adapter_store.acquire(adapter)
                except Exception as e:  # pylint: disable=broad-except
                    # Missing/corrupt artifact or an injected
                    # adapters.load fault: fail THIS request (404/503
                    # at the HTTP layer); the engine keeps serving.
                    fut.set_exception(e)
                    continue
                if aid is None:
                    # Every device adapter slot is pinned by a running
                    # request: back to the HEAD (the page-pressure
                    # back-pressure contract) until one frees.
                    self._queued_tokens_add(len(prompt))
                    self._ready.appendleft(
                        (prompt, max_new, temp, top_k, top_p, stops,
                         adapter, tref, on_token, deadline, fut))
                    break
                salt = self.adapter_store.cache_salt(adapter)
            plen = len(prompt)
            shared: List[int] = []
            keys: List[bytes] = []
            if self.paged:
                # Prefix cache: map the prompt's cached full pages to
                # their existing physical pages; prefill computes only
                # the suffix. At least ONE token must prefill (the
                # continuation samples from its logits), so a fully
                # cached prompt drops its last shared page.
                if self.prefix_cache is not None:
                    keys = PrefixCache.chain_keys(prompt,
                                                  self.page_size,
                                                  salt=salt)
                    shared = self.prefix_cache.lookup_acquire(
                        keys, record=False)
                    # Tiered cache: evicted-then-spilled pages extend
                    # the resident prefix (restore == fresh compute,
                    # bit-identical) before the hit/miss accounting —
                    # a restored page avoided the recompute exactly
                    # like a resident hit.
                    if self.spill_tier is not None and \
                            len(shared) < len(keys):
                        n_res0 = len(shared)
                        t_res = time.perf_counter()
                        self._restore_from_spill(keys, shared)
                        if tref is not None and len(shared) > n_res0:
                            tracing.record_span(
                                'engine.kv_restore', tref[0],
                                time.perf_counter() - t_res,
                                pages=len(shared) - n_res0)
                    self.prefix_cache.record_lookup(
                        len(shared), len(keys) - len(shared))
                    if len(shared) * self.page_size >= plen:
                        self.prefix_cache.release([shared.pop()])
                n_cached = len(shared) * self.page_size
                # The prefill scan writes positions [n_cached, bucket):
                # the real suffix needs pages; the padded tail hits
                # trash only where the table row is unallocated, so
                # allocate for plen (+1 for the first generated token).
                need = self.allocator.pages_needed(plen + 1,
                                                   self.page_size) \
                    - len(shared)
                # Construction guarantees the pool holds one
                # full-depth sequence and submit() bounds plen below
                # max_total_len, so a lone sequence always fits.
                assert plen + 1 <= (self.total_pages - 1) * self.page_size
                if self.prefix_cache is not None:
                    self._evict_for(need, tref)
                if not self.allocator.can_allocate(need):
                    # Pool exhausted: back to the HEAD and stop
                    # admitting until a sequence releases pages —
                    # later arrivals must not starve this one.
                    if self.prefix_cache is not None:
                        self.prefix_cache.release(shared)
                    if aid:
                        self.adapter_store.release(aid)
                    self._queued_tokens_add(len(prompt))
                    self._ready.appendleft(
                        (prompt, max_new, temp, top_k, top_p, stops,
                         adapter, tref, on_token, deadline, fut))
                    break
                pages = self.allocator.allocate(need)
                self.owned_pages[slot] = pages
                self.shared_pages[slot] = shared
                self.slot_keys[slot] = keys
                self.page_table[slot, :] = 0
                self.page_table[slot, :len(shared)] = shared
                self.page_table[slot, len(shared):len(shared) + need] = \
                    pages
                self.allocated_tokens[slot] = (len(shared) + need) * \
                    self.page_size
            else:
                n_cached = 0
            # Claim the slot BEFORE any device work: if prefill raises,
            # the loop's exception handler finds (and fails) this
            # future instead of leaving the client hanging.
            self.futures[slot] = fut
            self.outputs[slot] = list(prompt)
            self.prompt_len[slot] = plen
            self.prefill_frontier[slot] = n_cached
            # While prefilling, `pos` rides the frontier: the decode
            # loop's junk write for this inactive lane lands exactly
            # where the NEXT prefill chunk writes (before attending).
            self.pos[slot] = n_cached
            self.cur_token[slot] = 0
            limit = min(plen + max_new, self.max_total_len)
            if self.paged:
                # The pool bounds the deepest any sequence can get
                # (minus chunk-write lookahead); admission would
                # otherwise hand out a limit the allocator can never
                # satisfy even running alone.
                limit = min(limit, (self.total_pages - 1) *
                            self.page_size - self._write_lookahead)
            self.limits[slot] = limit
            self.temps[slot] = temp
            self.top_ks[slot] = top_k
            self.top_ps[slot] = top_p
            self.stop_ids[slot] = stops
            self.on_tokens[slot] = on_token
            self.deadlines[slot] = deadline
            self.slot_adapter[slot] = aid
            self.slot_adapter_name[slot] = adapter if aid else None
            self.prefilling[slot] = True
            self._prefill_order.append(slot)
            self._prefill_t0[slot] = time.perf_counter()
            self._slot_ctx[slot] = tref[0] if tref is not None else None
            if tref is not None:
                tracing.record_span('engine.queue_wait', tref[0],
                                    t_adm - tref[1], slot=slot)
                tracing.record_span('engine.admit', tref[0],
                                    time.perf_counter() - t_adm,
                                    slot=slot, prompt_len=plen,
                                    cached_tokens=n_cached)
            self.flight.record('admit', slot=slot, prompt_len=plen,
                               cached_tokens=n_cached,
                               queued=len(self._ready))
            self.metrics.admissions.inc()
            admitted = True
        return admitted

    def _evict_for(self, need: int, tref) -> None:
        """Prefix-cache eviction for an admission, with an
        'engine.kv_spill' span when the admitting request is traced
        and the eviction actually ran (untraced requests call
        straight through: no clock reads)."""
        cache = self.prefix_cache
        if tref is None:
            cache.evict_into(self.allocator, need)
            return
        ev0, sp0 = cache.evictions, cache.spilled_pages
        t0 = time.perf_counter()
        cache.evict_into(self.allocator, need)
        if cache.evictions > ev0:
            tracing.record_span(
                'engine.kv_spill', tref[0],
                time.perf_counter() - t0,
                evicted=cache.evictions - ev0,
                spilled=cache.spilled_pages - sp0)

    # -- chunked prefill ----------------------------------------------------
    def _chunk_shape(self, n: int, offset: int) -> int:
        """Compiled shape for an n-real-token prefill chunk at
        `offset`. Full chunks reuse the ONE prefill_chunk shape; the
        final partial chunk (and the whole suffix when chunking is
        off) buckets to a power of two, capped by the chunk size —
        so the compile ladder is log2(prefill_chunk) shapes, not
        log2(max_total_len)."""
        cap = self.prefill_chunk or self.max_total_len
        shape = min(_bucket(n, cap), cap)
        if self.paged and offset:
            # The chunk writes positions [offset, offset + shape):
            # cap the shape so the padded tail cannot run past the
            # page-table row — take_along_axis CLAMPS an out-of-range
            # logical page to the last column, which is a REAL page
            # holding the prompt tail, and the scatter would shred it.
            shape = min(shape,
                        self.pages_per_seq * self.page_size - offset)
            assert shape >= n
        return shape

    def _run_prefill_chunk(self, slot: int, offset: int, n: int):
        """Dispatch ONE prefill chunk: n real tokens of slot's prompt
        at absolute position `offset`. Returns the (device) logits of
        the chunk's last real token — the continuation samples from
        them when this was the final chunk."""
        faults.point('engine.prefill_chunk')
        shape = self._chunk_shape(n, offset)
        chunk = self.outputs[slot][offset:offset + n]
        padded = jnp.asarray(chunk + [0] * (shape - n), jnp.int32)
        lora_kw = self._slot_lora_args(slot)
        if self.paged and offset:
            fn = self._prefill_suffix_fn(shape)
            self.cache, last = fn(
                self.params, self.cache, padded, jnp.int32(n),
                jnp.int32(offset),
                jnp.asarray(self.page_table[slot:slot + 1]), **lora_kw)
        elif self.paged:
            fn = self._prefill_fn(shape)
            self.cache, last = fn(
                self.params, self.cache, padded, jnp.int32(n),
                jnp.asarray(self.page_table[slot:slot + 1]), **lora_kw)
        elif offset:
            fn = self._dense_suffix_fn(shape)
            self.cache, last = fn(
                self.params, self.cache, jnp.int32(slot), padded,
                jnp.int32(n), jnp.int32(offset), **lora_kw)
        else:
            fn = self._prefill_fn(shape)
            self.cache, last = fn(
                self.params, self.cache, jnp.int32(slot), padded,
                jnp.int32(n), **lora_kw)
        self.prefill_chunks_run += 1
        return last

    def _sample_first(self, slot: int, last_logits):
        """The continuation token from the final chunk's last-position
        logits (device value; fetched in one batched device_get per
        round by _prefill_work)."""
        temp = float(self.temps[slot])
        if temp > 0:
            self._rng, sub = jax.random.split(self._rng)
            return sample_tokens(
                sub, last_logits[None, :],
                jnp.full((1,), temp, jnp.float32),
                jnp.full((1,), int(self.top_ks[slot]), jnp.int32),
                jnp.full((1,), float(self.top_ps[slot]),
                         jnp.float32))[0]
        return jnp.argmax(last_logits)

    def _prefill_work(self) -> None:
        """Run at most `prefill_budget` suffix tokens of prefill, in
        prefill_chunk-sized dispatches, oldest admission first. Slots
        whose prompt completes sample their first token and join the
        decode loop; the budget bounds how long any single iteration
        defers the shared decode step (the anti-stall contract:
        chunked prefill never runs a dispatch longer than one chunk).
        With prefill_chunk=0 the whole suffix runs as ONE dispatch per
        slot (the legacy path) and the budget is unbounded."""
        budget = self.prefill_budget if self.prefill_chunk else None
        spent = 0
        chunks0 = self.prefill_chunks_run
        done: List[Any] = []    # (slot, first-token device scalar)
        while self._prefill_order:
            slot = self._prefill_order[0]
            plen = int(self.prompt_len[slot])
            offset = int(self.prefill_frontier[slot])
            n = plen - offset
            if self.prefill_chunk:
                n = min(n, self.prefill_chunk)
            if budget is not None and spent + n > budget:
                break   # budget spent: decode steps run first
            self.flight.record('chunk_dispatch', slot=slot,
                               offset=offset, n=n)
            t0 = time.perf_counter()
            try:
                last = self._run_prefill_chunk(slot, offset, n)
            except Exception as e:  # pylint: disable=broad-except
                if self._cache_lost():
                    raise  # every slot's history died with the cache
                # Crash-only isolation: the fault fired before the
                # device touched the cache (e.g. an injected
                # engine.prefill_chunk fault) — only THIS slot's
                # request fails; the rest keep decoding untouched.
                print(f'engine {self.engine_id}: prefill chunk for '
                      f'slot {slot} failed ({type(e).__name__}: {e}); '
                      f'failing only that request', flush=True)
                self._fail_slot(slot, e)
                continue
            self.metrics.prefill_chunk_seconds.observe(
                time.perf_counter() - t0)
            tracing.record_span('engine.prefill_chunk',
                                self._slot_ctx[slot],
                                time.perf_counter() - t0,
                                slot=slot, offset=offset, n=n)
            spent += n
            offset += n
            self.prefill_frontier[slot] = offset
            self.pos[slot] = offset
            if offset >= plen:
                self._prefill_order.popleft()
                done.append((slot, self._sample_first(slot, last)))
        self.last_prefill_tokens = spent
        if budget:
            self.metrics.prefill_budget_utilization.set(
                spent / budget)
        if self.stages > 1 and self.prefill_chunks_run > chunks0:
            # Closed-form bubble of this pass's chunk-microbatch
            # stream over the stage chain: M chunks through S stages
            # fill/drain (S-1)/(M+S-1) of the slot grid
            # (parallel/pipeline_schedule.make_inference_schedule —
            # the same span math the trainer's schedule asserts).
            from skypilot_tpu.parallel import pipeline_schedule
            sched = pipeline_schedule.make_inference_schedule(
                self.stages, self.prefill_chunks_run - chunks0)
            self._prefill_bubble = sched.bubble_fraction
        if not done:
            return
        # ONE host/device sync for every prompt that completed this
        # round (not one per admission).
        firsts = jax.device_get([first for _, first in done])
        for (slot, _), first in zip(done, firsts):
            self.cur_token[slot] = int(first)
            self.pos[slot] = int(self.prompt_len[slot])
            self.prefilling[slot] = False
            self.active[slot] = True
            self.metrics.prefill_seconds.observe(
                time.perf_counter() - self._prefill_t0[slot])

    def prefill_backlog_tokens(self) -> int:
        """Prompt-suffix tokens admitted but not yet prefilled (the
        chunked-prefill backlog; racy-but-harmless numpy reads, like
        the other scrape-time snapshots)."""
        return int(((self.prompt_len - self.prefill_frontier) *
                    self.prefilling).sum())

    def _grow_pages(self, lookahead: int = 1) -> None:
        """Before a decode step: every active slot about to write past
        its allocated tokens gets more pages (speculative chunks write
        `lookahead` tokens at once). On pool exhaustion the slot is
        PREEMPTED vLLM-style: its pages are released and the request
        re-queued with everything generated so far as the new prompt
        (recompute on re-admission), so page pressure stalls work
        instead of failing it. Requests that can never fit the pool
        fail loudly at admission. Sampled (temperature>0) requests may
        diverge across a preemption (fresh RNG); greedy decoding is
        unaffected."""
        preempted = []
        for slot in range(self.num_slots):
            if not self.active[slot]:
                continue
            # Clamp to the page-table row's capacity: the pipelined
            # loop's trailing round can write ONE position past a
            # finishing lane's limit, and legit writes never exceed
            # the table (construction headroom) — an out-of-capacity
            # junk write clamps into the lane's own released pages,
            # which every next owner rewrites before attending.
            need_tokens = min(int(self.pos[slot]) + lookahead,
                              self.pages_per_seq * self.page_size)
            exhausted = False
            while int(self.allocated_tokens[slot]) < need_tokens:
                # Allocation is logically contiguous: the next logical
                # page index == pages already allocated.
                logical = int(self.allocated_tokens[slot]) \
                    // self.page_size
                if not self.allocator.can_allocate(1) and \
                        self.prefix_cache is not None:
                    # Unreferenced cached prefixes yield before any
                    # live sequence gets preempted.
                    self.prefix_cache.evict_into(self.allocator, 1)
                if not self.allocator.can_allocate(1):
                    exhausted = True
                    break
                page = self.allocator.allocate(1)[0]
                self.owned_pages[slot].append(page)
                self.page_table[slot, logical] = page
                self.allocated_tokens[slot] += self.page_size
            if not exhausted:
                continue
            # Preempt: outputs-so-far become the prompt; the pending
            # cur_token is regenerated by the re-prefill. The adapter
            # ref drops with the slot (re-acquired — and reloaded if
            # evicted meanwhile — at re-admission).
            fut = self.futures[slot]
            adapter_name = self.slot_adapter_name[slot]
            remaining = int(self.limits[slot]) - len(self.outputs[slot])
            self.futures[slot] = None
            self.active[slot] = False
            self.preemptions += 1
            self.metrics.preemptions.inc()
            self.flight.record(
                'preempt', slot=slot,
                generated=len(self.outputs[slot]) -
                int(self.prompt_len[slot]))
            ctx = self._slot_ctx[slot]
            self._slot_ctx[slot] = None
            self._release_adapter(slot)
            self._release_slot_pages(slot, promote=False)
            if fut is not None:
                # The trace ctx rides the re-queued request: its
                # re-admission emits a second queue-wait span.
                tref = ((ctx, time.perf_counter())
                        if ctx is not None else None)
                preempted.append((list(self.outputs[slot]),
                                  max(remaining, 1),
                                  float(self.temps[slot]),
                                  int(self.top_ks[slot]),
                                  float(self.top_ps[slot]),
                                  self.stop_ids[slot],
                                  adapter_name, tref,
                                  self.on_tokens[slot],
                                  float(self.deadlines[slot]), fut))
                self._queued_tokens_add(len(self.outputs[slot]))
        # Back to the HEAD preserving pass order (repeated appendleft
        # would reverse it — an FCFS fairness inversion).
        self._ready.extendleft(reversed(preempted))

    def _release_slot_pages(self, slot: int, promote: bool) -> None:
        """Return a slot's pages: shared refs drop (page stays cached),
        own PROMPT-full pages are promoted into the prefix cache when
        `promote` (completion — their contents are final), the rest go
        back to the allocator. Preemption never promotes: its pages
        may hold half-written junk past the committed position."""
        cache = self.prefix_cache
        if cache is not None:
            own = self.owned_pages[slot]
            # Promote own pages BEFORE releasing the shared prefix
            # refs: LRU eviction pops oldest-first, and a chain is
            # only useful leaf-to-root — inserting leaves first makes
            # them evict before their prefixes (a prefix evicted
            # under a live suffix would orphan the suffix pages:
            # unreachable but resident).
            if promote and own:
                keys = self.slot_keys[slot]
                n_shared = len(self.shared_pages[slot])
                for i, page in enumerate(reversed(own)):
                    logical = n_shared + len(own) - 1 - i
                    if logical < len(keys) and \
                            cache.insert(keys[logical], page):
                        continue  # cache owns it now
                    self.allocator.release([page])
            else:
                self.allocator.release(own)
            cache.release(self.shared_pages[slot])
            self.shared_pages[slot] = []
            self.slot_keys[slot] = []
        else:
            self.allocator.release(self.owned_pages[slot])
        self.owned_pages[slot] = []
        self.page_table[slot, :] = 0
        self.allocated_tokens[slot] = 0

    def _emit(self, slot: int, tok: int) -> None:
        """Streaming callback for one committed token. A broken
        consumer (e.g. client hung up mid-stream) must not take down
        the shared scheduler loop: its callback is dropped and the
        request finishes normally."""
        cb = self.on_tokens[slot]
        if cb is None:
            return
        try:
            cb(tok)
        except Exception:  # pylint: disable=broad-except
            self.on_tokens[slot] = None

    def _finish_slot(self, slot: int) -> None:
        fut = self.futures[slot]
        self.futures[slot] = None
        self.active[slot] = False
        self.on_tokens[slot] = None
        self.deadlines[slot] = 0.0
        self._slot_ctx[slot] = None
        self._release_adapter(slot)
        was_prefilling = bool(self.prefilling[slot])
        if was_prefilling:
            # Cancelled mid-prefill: resolve with the prompt as-is
            # (nothing was generated) and drop the pending chunks.
            self.prefilling[slot] = False
            try:
                self._prefill_order.remove(slot)
            except ValueError:
                pass
        if self.paged:
            # Never promote a half-prefilled prompt's pages: pages
            # past the frontier were not written yet and would poison
            # the prefix cache.
            self._release_slot_pages(slot, promote=not was_prefilling)
        if fut is not None:
            fut.set_result(list(self.outputs[slot]))

    def _commit_token(self, slot: int, next_tok: int) -> bool:
        """Commit the slot's pending cur_token (append + stream +
        advance) and install `next_tok` as the new pending token;
        finish the slot (returning True) on limit/eos/stop. The ONE
        copy of the commit contract, shared by the plain, chunked,
        and speculative decode loops."""
        tok = int(self.cur_token[slot])
        self.outputs[slot].append(tok)
        self._emit(slot, tok)
        self.tokens_committed += 1
        self.metrics.tokens_committed.inc()
        self.pos[slot] += 1
        self.cur_token[slot] = int(next_tok)
        done = len(self.outputs[slot]) >= int(self.limits[slot])
        if self.eos_id is not None and tok == self.eos_id:
            done = True
        if tok in self.stop_ids[slot]:
            done = True
        if done:
            self._finish_slot(slot)
        return done

    def _lora_args(self) -> Dict[str, Any]:
        """Extra kwargs for a SHARED decode dispatch: the stacked
        adapter factors + per-slot adapter ids. {} when every lane is
        the base model — the zero-overhead fast path (the compiled
        base-only executables run untouched; the first adapter lane
        traces a second variant once)."""
        if self.adapter_store is None or not self.slot_adapter.any():
            return {}
        return {'lora': self.adapter_store.model_lora(),
                'adapter_ids': jnp.asarray(self.slot_adapter,
                                           jnp.int32)}

    def _slot_lora_args(self, slot: int) -> Dict[str, Any]:
        """Extra kwargs for a batch-1 prefill dispatch of `slot`."""
        aid = int(self.slot_adapter[slot])
        if not aid:
            return {}
        return {'lora': self.adapter_store.model_lora(),
                'adapter_ids': jnp.asarray([aid], jnp.int32)}

    def _decode_step(self) -> None:
        # Injection point BEFORE any dispatch and before the round
        # consumes RNG: a raised fault leaves state untouched, so the
        # retried round produces bit-identical tokens (greedy AND
        # sampled) — the crash-only containment contract the chaos
        # suite locks in.
        faults.point('engine.decode_step')
        if self.spec_k:
            self._spec_decode_step()
            return
        if self.decode_chunk > 1:
            self._chunk_decode_step()
            return
        if self.pipeline_decode:
            if self.stages > 1:
                self._staged_pipelined_decode_step()
            else:
                self._pipelined_decode_step()
            return
        self._rng, sub = jax.random.split(self._rng)
        extra = ()
        if self.paged:
            self._grow_pages()
            if not self.active.any():
                return  # _grow_pages may have failed the last slot
            extra = (jnp.asarray(self.page_table),)
        # Inactive slots decode at position 0 as a no-op: dense caches
        # get their row scribbled at position 0 (zeroed on prefill);
        # paged writes land in the trash page. PREFILLING slots ride
        # at their frontier, which the next chunk overwrites before
        # attending.
        self.cache, sampled = self._decode(
            self.params, self.cache,
            jnp.asarray(self.cur_token), jnp.asarray(self.pos),
            jnp.asarray(self.temps), jnp.asarray(self.top_ks),
            jnp.asarray(self.top_ps), sub, *extra,
            **self._lora_args())
        sampled = self._fetch_tokens(sampled)
        self.decode_calls += 1
        self.metrics.decode_steps.inc()
        for slot in range(self.num_slots):
            if not self.active[slot]:
                continue
            self._commit_token(slot, int(sampled[slot]))

    def _trace_decode_round(self, dur: float) -> None:
        """One 'engine.decode_round' span per traced slot riding this
        round — the request's occupancy of the shared dispatch. Slots
        that finished inside the round already cleared their ctx (the
        final round is not attributed; the one-round skew is
        harmless)."""
        batch = int(self.active.sum())
        for slot in range(self.num_slots):
            ctx = self._slot_ctx[slot]
            if ctx is None or not (self.active[slot] or
                                   self.prefilling[slot]):
                continue
            tracing.record_span('engine.decode_round', ctx, dur,
                                slot=slot, pos=int(self.pos[slot]),
                                batch=batch)

    def _fetch_tokens(self, dev) -> 'np.ndarray':
        """device_get with decode-stall accounting: the wall time the
        host spends blocked here is exactly the serial host/device
        bubble pipelining exists to hide."""
        faults.point('engine.device_get')
        t0 = time.perf_counter()
        out = np.asarray(jax.device_get(dev))
        stall = time.perf_counter() - t0
        self.decode_stall_s += stall
        self.metrics.decode_stall_seconds.inc(stall)
        if tracing.enabled():
            # The stall is shared by the whole round: attribute ONE
            # span to the first traced active slot (a representative,
            # not a per-slot fan-out).
            for slot in range(self.num_slots):
                ctx = self._slot_ctx[slot]
                if ctx is not None and self.active[slot]:
                    tracing.record_span(
                        'engine.device_get', ctx, stall,
                        stall_ms=round(stall * 1e3, 3))
                    break
        return out

    # -- pipelined decode ---------------------------------------------------
    def _dispatch_round(self, inflight: Optional[Dict[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
        """Dispatch the next decode round WITHOUT waiting for the
        in-flight one: continuing lanes feed the in-flight round's
        (device-resident) sampled tokens straight back as inputs —
        no host round-trip — at position +1; lanes that joined since
        (fresh prefills) take their host-side first token. A lane the
        pending commit will retire gets a junk write one past its
        last position (write-before-read keeps it harmless)."""
        if self.paged:
            # +1 lookahead when a round is still uncommitted: this
            # dispatch writes at pos+1 for continuing lanes.
            self._grow_pages(lookahead=2 if inflight is not None
                             else 1)
            if not self.active.any():
                return None
        if inflight is None:
            cur = jnp.asarray(self.cur_token)
            pos = self.pos.copy()
        else:
            cont = np.array(
                [bool(inflight['mask'][s]) and bool(self.active[s])
                 and self.futures[s] is inflight['futs'][s]
                 for s in range(self.num_slots)])
            pos = np.where(cont, inflight['pos'] + 1,
                           self.pos).astype(np.int32)
            cur = jnp.where(jnp.asarray(cont), inflight['sampled'],
                            jnp.asarray(self.cur_token))
        extra = (jnp.asarray(self.page_table),) if self.paged else ()
        self._rng, sub = jax.random.split(self._rng)
        self.cache, sampled = self._decode(
            self.params, self.cache, cur, jnp.asarray(pos),
            jnp.asarray(self.temps), jnp.asarray(self.top_ks),
            jnp.asarray(self.top_ps), sub, *extra,
            **self._lora_args())
        self.decode_calls += 1
        self.metrics.decode_steps.inc()
        return {'sampled': sampled, 'mask': self.active.copy(),
                'pos': pos, 'futs': list(self.futures)}

    def _commit_round(self, inflight: Dict[str, Any]) -> None:
        """Fetch + commit a dispatched round. Lanes whose request
        finished, was preempted, or was replaced since dispatch are
        discarded (their round-N+1 token belongs to nobody)."""
        sampled = self._fetch_tokens(inflight['sampled'])
        for slot in range(self.num_slots):
            if not inflight['mask'][slot]:
                continue
            if not self.active[slot] or \
                    self.futures[slot] is not inflight['futs'][slot]:
                continue
            self._commit_token(slot, int(sampled[slot]))

    def _pipelined_decode_step(self) -> None:
        """One pipelined iteration: dispatch round N+1 FIRST (device
        starts computing), then fetch + commit round N while N+1 runs
        — stop-detection, streaming callbacks, and future resolution
        all overlap device compute. Greedy outputs are token-for-token
        the unpipelined loop's: committed tokens come from the same
        round sequence; only the trailing round after a drain is
        speculative waste."""
        inflight = self._inflight
        nxt = self._dispatch_round(inflight) if self.active.any() \
            else None
        if inflight is not None:
            self._commit_round(inflight)
        self._inflight = nxt

    # -- staged pipelined decode (the S-deep ring) --------------------------
    def _group_slice(self, g: int) -> slice:
        width = self.num_slots // self.stages
        return slice(g * width, (g + 1) * width)

    def _dispatch_group_round(self, g: int,
                              inflight: Optional[Dict[str, Any]]
                              ) -> Optional[Dict[str, Any]]:
        """_dispatch_round on one slot GROUP: the width-W slice of
        the slot arrays rides the S-stage chain while the other
        groups' rounds occupy other stages. Same ring-feedback
        contract as the unstaged path — continuing lanes feed the
        in-flight round's device-resident tokens straight back."""
        sl = self._group_slice(g)
        if not self.active[sl].any():
            return None
        if inflight is None:
            cur = jnp.asarray(self.cur_token[sl])
            pos = self.pos[sl].copy()
        else:
            base = sl.start
            cont = np.array(
                [bool(inflight['mask'][i]) and
                 bool(self.active[base + i]) and
                 self.futures[base + i] is inflight['futs'][i]
                 for i in range(sl.stop - sl.start)])
            pos = np.where(cont, inflight['pos'] + 1,
                           self.pos[sl]).astype(np.int32)
            cur = jnp.where(jnp.asarray(cont), inflight['sampled'],
                            jnp.asarray(self.cur_token[sl]))
        self._rng, sub = jax.random.split(self._rng)
        self.cache, sampled = self._decode(
            self.params, self.cache, cur, jnp.asarray(pos),
            jnp.asarray(self.temps[sl]), jnp.asarray(self.top_ks[sl]),
            jnp.asarray(self.top_ps[sl]), sub,
            jnp.asarray(self.page_table[sl]),
            **self._group_lora_args(sl))
        self.decode_calls += 1
        self.metrics.decode_steps.inc()
        return {'sampled': sampled, 'mask': self.active[sl].copy(),
                'pos': pos, 'futs': list(self.futures[sl])}

    def _group_lora_args(self, sl: slice) -> Dict[str, Any]:
        """_lora_args for one slot group's width-W dispatch."""
        if self.adapter_store is None or \
                not self.slot_adapter[sl].any():
            return {}
        return {'lora': self.adapter_store.model_lora(),
                'adapter_ids': jnp.asarray(self.slot_adapter[sl],
                                           jnp.int32)}

    def _commit_group_round(self, g: int,
                            inflight: Dict[str, Any]) -> None:
        """Fetch + commit one group's dispatched round (lane i is
        slot g*W + i); discard rules match _commit_round."""
        sampled = self._fetch_tokens(inflight['sampled'])
        base = self._group_slice(g).start
        for i in range(len(sampled)):
            slot = base + i
            if not inflight['mask'][i]:
                continue
            if not self.active[slot] or \
                    self.futures[slot] is not inflight['futs'][i]:
                continue
            self._commit_token(slot, int(sampled[i]))

    def _staged_pipelined_decode_step(self) -> None:
        """One iteration of the S-deep decode ring: slots partition
        into `stages` contiguous groups; dispatch EVERY group's next
        round through the stage chain first (async — group g+1's
        stage-0 pass overlaps group g's stage-1 pass, so the S
        in-flight rounds occupy different stages simultaneously),
        then fetch + commit each group's previous round. Greedy
        outputs are token-for-token the unpipelined loop's: each
        lane's successive rounds are still sequential."""
        self._grow_pages(lookahead=2)
        nxt: List[Optional[Dict[str, Any]]] = []
        for g in range(self.stages):
            nxt.append(self._dispatch_group_round(
                g, self._group_inflight[g]))
        for g in range(self.stages):
            if self._group_inflight[g] is not None:
                self._commit_group_round(g, self._group_inflight[g])
        self._group_inflight = nxt

    def _chunk_decode_step(self) -> None:
        """One chunked round: decode_chunk tokens for every active
        slot in ONE dispatch; commit host-side, truncating each slot
        at its limit/eos/stop (a finished slot's remaining chunk
        tokens are discarded — up to N-1 wasted steps, the price of
        amortizing dispatch overhead)."""
        n = self.decode_chunk
        extra = ()
        if self.paged:
            # The chunk writes positions pos..pos+n-1 (+1 commit room).
            self._grow_pages(lookahead=n)
            if not self.active.any():
                return
            extra = (jnp.asarray(self.page_table),)
        was_active = self.active.copy()
        self.cache, toks, self._rng = self._chunk_decode(
            self.params, self.cache, jnp.asarray(self.cur_token),
            jnp.asarray(self.pos), jnp.asarray(self.temps),
            jnp.asarray(self.top_ks), jnp.asarray(self.top_ps),
            self._rng, *extra, **self._lora_args())
        toks = self._fetch_tokens(toks)               # [n, slots]
        self.decode_calls += 1
        self.metrics.decode_steps.inc()
        for slot in range(self.num_slots):
            if not was_active[slot]:
                continue
            for i in range(n):
                if self._commit_token(slot, int(toks[i, slot])):
                    break  # finished: discard the chunk's tail

    def _spec_decode_step(self) -> None:
        """One speculative round: draft K tokens per slot (host-side
        prompt lookup), verify the whole [current ++ drafts] chunk in
        ONE model call, commit the model-confirmed prefix — 1..K+1
        tokens per call. Rejected drafts leave stale cache entries
        above the new position; the next chunk overwrites them before
        attending (the chunked-attention write-before-read contract)."""
        k = self.spec_k
        drafts = self._draft()                         # [slots, K]
        extra = ()
        if self.paged:
            # The chunk writes positions pos..pos+K: allocate K+1 ahead.
            self._grow_pages(lookahead=k + 1)
            if not self.active.any():
                return
            extra = (jnp.asarray(self.page_table),)
        chunk = np.concatenate([self.cur_token[:, None], drafts], axis=1)
        self._rng, sub = jax.random.split(self._rng)
        self.cache, y = self._decode(
            self.params, self.cache, jnp.asarray(chunk),
            jnp.asarray(self.pos), jnp.asarray(self.temps),
            jnp.asarray(self.top_ks), jnp.asarray(self.top_ps), sub,
            *extra, **self._lora_args())
        y = self._fetch_tokens(y)                      # [slots, K+1]
        self.decode_calls += 1
        self.metrics.decode_steps.inc()
        for slot in range(self.num_slots):
            if not self.active[slot]:
                continue
            accept = 0
            while (accept < k and
                   int(drafts[slot, accept]) == int(y[slot, accept])):
                accept += 1
            # Commit: the pending current token, then every accepted
            # draft; each commit's successor is the model's own token
            # for that position (y), so the final pending token is the
            # first correction. (The accepted-prefix invariant makes
            # cur_token equal the next commit at every step, so the
            # shared _commit_token applies unchanged.)
            for nxt in y[slot, :accept + 1]:
                if self._commit_token(slot, int(nxt)):
                    break
