"""Llama-3-family decoder in flax.linen with logical sharding axes.

Recipe model #2 (BASELINE.md configs 2/4): RMSNorm, rotary position
embeddings, grouped-query attention, SwiGLU MLP, untied LM head.
Same logical-axis scheme as models/gpt.py so one rules table drives
DP×FSDP×TP for both.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.ops import attention as attention_ops

Dtype = Any


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """RoPE frequency rescaling (HF config.json `rope_scaling`).

    `llama3` is the Llama 3.1/3.2 long-context rule: frequencies whose
    wavelength exceeds the original context are divided by `factor`,
    high frequencies are kept, and a smooth ramp interpolates between
    `low_freq_factor` and `high_freq_factor` (reference recipes:
    `llm/llama-3_1-finetuning/` serve these checkpoints). `linear` is
    classic position-interpolation (all frequencies / factor).
    """
    rope_type: str = 'llama3'
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    max_seq_len: int = 8192
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    embed_dim: int = 4096
    mlp_dim: int = 14336
    rope_theta: float = 500_000.0
    rope_scaling: Optional[RopeScaling] = None
    norm_eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    # LM-head logits precision. None = f32 (the safe default for this
    # family; GPT defaults to bf16 — see GPTConfig.logits_dtype for the
    # HBM-traffic rationale). Set jnp.bfloat16 to halve logits traffic.
    logits_dtype: Optional[Dtype] = None
    remat: bool = False
    # Paged KV cache (serving): page size in tokens and the physical
    # page-pool size. Used only when decode calls pass `page_indices`;
    # page 0 is the engine's trash page for unallocated table entries.
    kv_page_size: int = 16
    kv_total_pages: int = 128
    # KV page storage format: 'bf16' stores pages in `dtype`; 'int8'
    # stores int8 pages plus parallel f32 per-page-slot scale arrays
    # (quantize on write, dequantize inside the attention gather —
    # ops/paged_attention.py). Roughly halves pool bytes per token,
    # i.e. ~2x slots / prefix-cache residency at the same HBM.
    # Requires the paged cache (serve_lm --continuous-batching).
    kv_dtype: str = 'bf16'
    # Qwen2-family variant: biases on the q/k/v projections (the only
    # architectural delta from Llama; o_proj and the MLP stay
    # bias-free).
    qkv_bias: bool = False

    @classmethod
    def llama3_8b(cls, **kw) -> 'LlamaConfig':
        return cls(**kw)

    @classmethod
    def llama3_70b(cls, **kw) -> 'LlamaConfig':
        return cls(num_layers=80, num_heads=64, num_kv_heads=8,
                   embed_dim=8192, mlp_dim=28672, **kw)

    @classmethod
    def tiny(cls, **kw) -> 'LlamaConfig':
        return cls(vocab_size=512, max_seq_len=256, num_layers=2,
                   num_heads=4, num_kv_heads=2, embed_dim=128, mlp_dim=384,
                   **kw)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def rope_inv_freq(d_half: int, theta: float,
                  scaling: Optional[RopeScaling] = None) -> jax.Array:
    """Per-pair inverse frequencies [d_half], with optional rescaling."""
    freqs = 1.0 / (theta ** (jnp.arange(d_half, dtype=jnp.float32) / d_half))
    if scaling is None:
        return freqs
    if scaling.rope_type == 'linear':
        return freqs / scaling.factor
    if scaling.rope_type != 'llama3':
        raise ValueError(f'unsupported rope_type {scaling.rope_type!r}')
    old_ctx = float(scaling.original_max_position_embeddings)
    low_wavelen = old_ctx / scaling.low_freq_factor
    high_wavelen = old_ctx / scaling.high_freq_factor
    wavelen = 2.0 * jnp.pi / freqs
    smooth = (old_ctx / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor)
    interp = ((1.0 - smooth) * freqs / scaling.factor + smooth * freqs)
    scaled = jnp.where(wavelen > low_wavelen, freqs / scaling.factor,
                       jnp.where(wavelen < high_wavelen, freqs, interp))
    return scaled


def apply_rope(x: jax.Array, positions: jax.Array, theta: float,
               scaling: Optional[RopeScaling] = None) -> jax.Array:
    """x: [B, S, H, D]; rotary embedding on the last dim."""
    d_half = x.shape[-1] // 2
    freqs = rope_inv_freq(d_half, theta, scaling)
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # B,S,1,Dh
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            'scale',
            nn.with_logical_partitioning(nn.initializers.ones_init(),
                                         ('norm',)),
            (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * jax.lax.rsqrt(var + self.eps) * scale
        return out.astype(self.dtype)


def _proj(features: int, axes, dtype, name: str,
          use_bias: bool = False) -> nn.Dense:
    return nn.Dense(
        features, use_bias=use_bias, dtype=dtype, name=name,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (axes[-1],)))


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 lora: Optional[dict] = None,
                 adapter_ids: Optional[jax.Array] = None,
                 lora_scale: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        batch, seq, _ = x.shape
        hd = cfg.head_dim

        def _lora(name, y, inp):
            # LoRA delta on a projection output (models/lora.py):
            # single-adapter in training, per-row adapter gather in
            # the serving engine. No-op (and no extra compute) when
            # this layer/projection carries no adapter factors.
            if lora is None or name not in lora:
                return y
            return lora_lib.apply_delta(y, inp, lora[name],
                                        adapter_ids, lora_scale)

        q = _proj(cfg.num_heads * hd, ('embed', 'heads'),
                  cfg.dtype, 'wq', cfg.qkv_bias)(x)
        k = _proj(cfg.num_kv_heads * hd, ('embed', 'heads'),
                  cfg.dtype, 'wk', cfg.qkv_bias)(x)
        v = _proj(cfg.num_kv_heads * hd, ('embed', 'heads'),
                  cfg.dtype, 'wv', cfg.qkv_bias)(x)
        # Multi-tenant QKV LoRA: when the fused kernel path is active
        # (ops/pallas_paged.py dispatch state, resolved at trace time)
        # and all three projections carry stacked per-slot factors, the
        # three gather+matmul chains collapse into ONE pallas dispatch.
        # The caller-side scale/cast below matches lora.apply_delta
        # numerics exactly; wq/wk/wv fall back to per-projection
        # apply_delta otherwise (training, single-adapter, XLA impl).
        fused_lora = None
        if (lora is not None and adapter_ids is not None
                and all(t in lora for t in ('wq', 'wk', 'wv'))):
            from skypilot_tpu.ops import pallas_paged
            fused_lora = pallas_paged.lora_fusion_impl(
                cfg.kv_dtype == 'int8')
        if fused_lora is not None:
            from skypilot_tpu.ops import pallas_paged
            dq, dk, dv = pallas_paged.fused_qkv_lora_delta(
                x, lora['wq'], lora['wk'], lora['wv'], adapter_ids,
                interpret=fused_lora == 'fused_interpret')
            q = q + (lora_scale * dq).astype(q.dtype)
            k = k + (lora_scale * dk).astype(k.dtype)
            v = v + (lora_scale * dv).astype(v.dtype)
        else:
            q = _lora('wq', q, x)
            k = _lora('wk', k, x)
            v = _lora('wv', v, x)
        q = q.reshape(batch, seq, cfg.num_heads, hd)
        k = k.reshape(batch, seq, cfg.num_kv_heads, hd)
        v = v.reshape(batch, seq, cfg.num_kv_heads, hd)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

        kv_quant = cfg.kv_dtype == 'int8'
        if cfg.kv_dtype not in ('bf16', 'int8'):
            raise ValueError(f'unsupported kv_dtype {cfg.kv_dtype!r} '
                             f"(choices: 'bf16', 'int8')")
        if kv_quant and decode and page_indices is None:
            raise ValueError(
                'kv_dtype=int8 requires the paged KV cache (the dense '
                'per-slot cache has no scale storage); serve with '
                '--continuous-batching and a paged-capable pool')

        def _page_vars():
            shape = (cfg.num_kv_heads, cfg.kv_total_pages,
                     cfg.kv_page_size, hd)
            k_pages = self.variable(
                'cache', 'k_pages', jnp.zeros, shape,
                jnp.int8 if kv_quant else cfg.dtype)
            v_pages = self.variable(
                'cache', 'v_pages', jnp.zeros, shape,
                jnp.int8 if kv_quant else cfg.dtype)
            if not kv_quant:
                return k_pages, v_pages, None, None
            # Parallel scale pages: one f32 per cached token (page
            # slot), shared across KV heads — scales travel with
            # their physical page so alloc/free/prefix-sharing need
            # no storage-format awareness.
            sshape = (cfg.kv_total_pages, cfg.kv_page_size)
            return (k_pages, v_pages,
                    self.variable('cache', 'k_scales', jnp.zeros,
                                  sshape, jnp.float32),
                    self.variable('cache', 'v_scales', jnp.zeros,
                                  sshape, jnp.float32))

        if decode and seq > 1:
            # CHUNKED decode: many tokens in one forward pass, both
            # paged and dense — `prefill` (static) selects chunk-local
            # attention (empty-cache contract, flash-eligible);
            # otherwise the chunk attends the full history (speculative
            # verification chunks at arbitrary per-row offsets).
            if page_indices is not None:
                from skypilot_tpu.ops import paged_attention as paged_ops
                k_pages, v_pages, k_sc, v_sc = _page_vars()
                if kv_quant:
                    (k_pages.value, v_pages.value, k_sc.value,
                     v_sc.value) = paged_ops.write_kv_chunk_quant(
                        k_pages.value, v_pages.value, k_sc.value,
                        v_sc.value, k, v, positions, page_indices)
                else:
                    k_pages.value, v_pages.value = \
                        paged_ops.write_kv_chunk(
                            k_pages.value, v_pages.value, k, v,
                            positions, page_indices)
                if prefill:
                    # Chunk-local attention reads the chunk's own
                    # bf16 K/V (exact); later chunks/decodes read the
                    # quantized pages — the storage contract.
                    out = attention_ops.dot_product_attention(
                        q, k, v, causal=True)
                else:
                    out = paged_ops.paged_chunk_attention(
                        q, k_pages.value, v_pages.value, positions,
                        page_indices,
                        k_scales=k_sc.value if kv_quant else None,
                        v_scales=v_sc.value if kv_quant else None,
                        ).astype(cfg.dtype)
            else:
                cached_k = self.variable(
                    'cache', 'cached_key', jnp.zeros,
                    (batch, cfg.max_seq_len, cfg.num_kv_heads, hd),
                    cfg.dtype)
                cached_v = self.variable(
                    'cache', 'cached_value', jnp.zeros,
                    (batch, cfg.max_seq_len, cfg.num_kv_heads, hd),
                    cfg.dtype)
                # `prefill` (static): the caller guarantees the cache
                # holds nothing below this chunk, so attention stays
                # chunk-local (S x S, flash-eligible) instead of
                # materializing S x max_seq_len f32 scores.
                out, cached_k.value, cached_v.value = \
                    attention_ops.chunked_cache_attention(
                        q, k, v, cached_k.value, cached_v.value,
                        positions, chunk_only=prefill)
                out = out.astype(cfg.dtype)
        elif decode:
            # Incremental decoding: one token in, KV cache with PER-ROW
            # write positions — the shared serving-cache contract
            # (ops.attention.cached_decode_attention), which is what
            # lets continuous batching decode slots at different depths
            # in one step (models/batching.py).
            if page_indices is not None:
                # Paged KV (vLLM-style): K/V live in a shared physical
                # page pool; this sequence's pages come from the
                # engine-provided table (ops/paged_attention.py).
                from skypilot_tpu.ops import paged_attention as paged_ops
                k_pages, v_pages, k_sc, v_sc = _page_vars()
                if kv_quant:
                    (k_pages.value, v_pages.value, k_sc.value,
                     v_sc.value) = paged_ops.write_kv_quant(
                        k_pages.value, v_pages.value, k_sc.value,
                        v_sc.value, k[:, 0], v[:, 0],
                        positions[:, 0], page_indices)
                else:
                    k_pages.value, v_pages.value = paged_ops.write_kv(
                        k_pages.value, v_pages.value, k[:, 0], v[:, 0],
                        positions[:, 0], page_indices)
                out = paged_ops.paged_decode_attention(
                    q[:, 0], k_pages.value, v_pages.value,
                    lengths=positions[:, 0] + 1,
                    page_indices=page_indices,
                    k_scales=k_sc.value if kv_quant else None,
                    v_scales=v_sc.value if kv_quant else None)
                out = out[:, None].astype(cfg.dtype)
            else:
                cached_k = self.variable(
                    'cache', 'cached_key', jnp.zeros,
                    (batch, cfg.max_seq_len, cfg.num_kv_heads, hd),
                    cfg.dtype)
                cached_v = self.variable(
                    'cache', 'cached_value', jnp.zeros,
                    (batch, cfg.max_seq_len, cfg.num_kv_heads, hd),
                    cfg.dtype)
                out, cached_k.value, cached_v.value = \
                    attention_ops.cached_decode_attention(
                        q, k, v, cached_k.value, cached_v.value,
                        positions[:, 0])
                out = out.astype(cfg.dtype)
        else:
            q = nn.with_logical_constraint(q,
                                           ('batch', 'seq', 'heads', 'kv'))
            k = nn.with_logical_constraint(k,
                                           ('batch', 'seq', 'heads', 'kv'))
            v = nn.with_logical_constraint(v,
                                           ('batch', 'seq', 'heads', 'kv'))
            out = attention_ops.dot_product_attention(q, k, v, causal=True)
        out = out.reshape(batch, seq, cfg.num_heads * hd)
        return _lora('wo',
                     _proj(cfg.embed_dim, ('heads', 'embed'), cfg.dtype,
                           'wo')(out), out)


class FeedForward(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array,
                 lora: Optional[dict] = None,
                 adapter_ids: Optional[jax.Array] = None,
                 lora_scale: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config

        def _lora(name, y, inp):
            if lora is None or name not in lora:
                return y
            return lora_lib.apply_delta(y, inp, lora[name],
                                        adapter_ids, lora_scale)

        gate = _lora('w_gate',
                     _proj(cfg.mlp_dim, ('embed', 'mlp'), cfg.dtype,
                           'w_gate')(x), x)
        up = _lora('w_up',
                   _proj(cfg.mlp_dim, ('embed', 'mlp'), cfg.dtype,
                         'w_up')(x), x)
        h = nn.silu(gate) * up
        h = nn.with_logical_constraint(h, ('batch', 'seq', 'mlp'))
        return _lora('w_down',
                     _proj(cfg.embed_dim, ('mlp', 'embed'), cfg.dtype,
                           'w_down')(h), h)


class Block(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 lora: Optional[dict] = None,
                 adapter_ids: Optional[jax.Array] = None,
                 lora_scale: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        x = x + Attention(cfg, name='attn')(
            RMSNorm(cfg.norm_eps, cfg.dtype, name='attn_norm')(x), positions,
            decode, page_indices, prefill, lora, adapter_ids, lora_scale)
        x = x + FeedForward(cfg, name='mlp')(
            RMSNorm(cfg.norm_eps, cfg.dtype, name='mlp_norm')(x),
            lora, adapter_ids, lora_scale)
        return nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))


def embed_tokens(params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Functional input embedding (shared with the pipeline trainer's
    stage-0 op, parallel/pipeline.py — mirrors gpt.embed_tokens)."""
    return params['tok_embed'].astype(cfg.dtype)[tokens]


def final_norm_logits(params, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Functional final RMSNorm + untied LM head (the pipeline
    trainer's last-stage op; numerics mirror Llama.__call__)."""
    scale = params['final_norm']['scale'].astype(jnp.float32)
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    x_n = (x32 * jax.lax.rsqrt(var + cfg.norm_eps) * scale).astype(
        cfg.dtype)
    return jnp.einsum('bse,ev->bsv', x_n,
                      params['lm_head'].astype(cfg.dtype),
                      preferred_element_type=(cfg.logits_dtype or
                                              jnp.float32))


class Llama(nn.Module):
    """Llama decoder; __call__ returns logits [B, S, vocab] (f32).

    `return_hidden=True` returns the post-final_norm hidden states
    [B, S, embed] instead — the trainer's fused blockwise loss
    (ops/fused_xent.py) consumes them against `lm_head` directly, so
    the [B, S, vocab] logits (the HBM high-water mark at 128k+
    vocabs) are never formed.
    """
    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 return_hidden: bool = False,
                 lora: Optional[dict] = None,
                 adapter_ids: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        batch, seq = tokens.shape
        # `lora` = {'scale': f32, 'layers': {'layer_i': {target:
        # {'a', 'b'}}}} (models/lora.py). Per-layer factors thread
        # into each block; `adapter_ids` [batch] selects each row's
        # adapter from stacked factors (None = single-adapter mode).
        lora_scale = lora['scale'] if lora is not None else None
        lora_layers = lora['layers'] if lora is not None else {}
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
        embed = self.param(
            'tok_embed',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('vocab', 'table_embed')),
            (cfg.vocab_size, cfg.embed_dim), jnp.float32)
        x = embed.astype(cfg.dtype)[tokens]
        x = nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))

        block = Block
        if cfg.remat:
            block = nn.remat(Block, prevent_cse=False,
                             static_argnums=(3, 5))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f'layer_{i}')(x, positions, decode,
                                              page_indices, prefill,
                                              lora_layers.get(f'layer_{i}'),
                                              adapter_ids, lora_scale)
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name='final_norm')(x)
        head = self.param(
            'lm_head',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('embed', 'vocab')),
            (cfg.embed_dim, cfg.vocab_size), jnp.float32)
        if return_hidden:
            # Head param is registered above so init() is identical
            # with or without the fused-loss path.
            return nn.with_logical_constraint(
                x, ('batch', 'seq', 'act_embed'))
        # bf16 operands, accumulation dtype from cfg.logits_dtype
        # (None = f32: MXU-native rate, f32-safe softmax numerics).
        logits = jnp.einsum('bse,ev->bsv', x.astype(cfg.dtype),
                            head.astype(cfg.dtype),
                            preferred_element_type=(cfg.logits_dtype or
                                                    jnp.float32))
        return nn.with_logical_constraint(logits, ('batch', 'seq', 'vocab'))


class LlamaStage(nn.Module):
    """One pipeline stage of the Llama decoder (staged serving).

    Runs layers [lo, hi) with ABSOLUTE layer names (`layer_{i}`), so a
    full `Llama` param/cache tree splits into per-stage trees by key
    and the wire-format keys of the paged KV pool (kv_transfer chain
    export) are the union of the stage trees — identical to the
    unstaged layout. The first stage owns `tok_embed` and maps tokens
    [B, S] -> hidden [B, S, embed]; the last stage owns `final_norm` +
    `lm_head` and maps hidden -> logits [B, S, vocab]; interior stages
    are hidden -> hidden. Layer application is sequential and
    dtype-identical to `Llama.__call__`, so chaining the S stages on
    the same weights reproduces the full model bit-for-bit.
    """
    config: LlamaConfig
    lo: int
    hi: int
    first: bool
    last: bool

    @nn.compact
    def __call__(self, x: jax.Array,
                 positions: Optional[jax.Array] = None,
                 decode: bool = False,
                 page_indices: Optional[jax.Array] = None,
                 prefill: bool = False,
                 lora: Optional[dict] = None,
                 adapter_ids: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        # The WHOLE lora stack threads through every stage; each stage
        # gathers only its own layers' factors below (the rest are
        # dead inputs XLA drops), so the engine passes one pytree.
        lora_scale = lora['scale'] if lora is not None else None
        lora_layers = lora['layers'] if lora is not None else {}
        if self.first:
            tokens = x
            batch, seq = tokens.shape
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(seq),
                                             (batch, seq))
            embed = self.param(
                'tok_embed',
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02),
                    ('vocab', 'table_embed')),
                (cfg.vocab_size, cfg.embed_dim), jnp.float32)
            x = embed.astype(cfg.dtype)[tokens]
        else:
            batch, seq = x.shape[:2]
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(seq),
                                             (batch, seq))
        x = nn.with_logical_constraint(x, ('batch', 'seq', 'act_embed'))

        block = Block
        if cfg.remat:
            block = nn.remat(Block, prevent_cse=False,
                             static_argnums=(3, 5))
        for i in range(self.lo, self.hi):
            x = block(cfg, name=f'layer_{i}')(x, positions, decode,
                                              page_indices, prefill,
                                              lora_layers.get(f'layer_{i}'),
                                              adapter_ids, lora_scale)
        if not self.last:
            return x
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name='final_norm')(x)
        head = self.param(
            'lm_head',
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ('embed', 'vocab')),
            (cfg.embed_dim, cfg.vocab_size), jnp.float32)
        logits = jnp.einsum('bse,ev->bsv', x.astype(cfg.dtype),
                            head.astype(cfg.dtype),
                            preferred_element_type=(cfg.logits_dtype or
                                                    jnp.float32))
        return nn.with_logical_constraint(logits, ('batch', 'seq', 'vocab'))
