"""Workspaces: named multi-tenant namespaces.

Reference: sky/workspaces/core.py — per-workspace enabled clouds and
config overlays; clusters are tagged with their workspace. Round-1
scope: workspace registry in config + the active-workspace selector;
per-workspace cloud filtering hooks into check.get_cached_enabled_clouds.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_config

_ENV = 'SKYPILOT_WORKSPACE'
DEFAULT = 'default'


def active_workspace() -> str:
    return os.environ.get(_ENV) or str(
        sky_config.get_nested(('active_workspace',), DEFAULT))


def get_workspaces() -> Dict[str, Dict[str, Any]]:
    out = sky_config.get_nested(('workspaces',), {}) or {}
    if DEFAULT not in out:
        out = {DEFAULT: {}, **out}
    return out


def get_workspace(name: Optional[str] = None) -> Dict[str, Any]:
    name = name or active_workspace()
    workspaces = get_workspaces()
    if name not in workspaces:
        raise exceptions.SkyError(
            f'Workspace {name!r} not defined; configure `workspaces:` in '
            'config. Known: ' + ', '.join(sorted(workspaces)))
    return workspaces[name] or {}


def allowed_clouds(name: Optional[str] = None) -> Optional[List[str]]:
    """None = all enabled clouds; else the workspace's allow-list."""
    ws = get_workspace(name)
    allowed = ws.get('allowed_clouds')
    if allowed is None:
        return None
    return [str(c).lower() for c in allowed]
