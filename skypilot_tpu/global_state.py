"""Server-side global state: clusters, history, events, storage.

Reference: sky/global_user_state.py (3465 LoC, SQLAlchemy). Stdlib
sqlite here (utils/db_utils.py); handles are pickled like the
reference's ResourceHandle column.
"""
from __future__ import annotations

import functools
import json
import pickle
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import db_utils
from skypilot_tpu.utils.status_lib import ClusterStatus

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS clusters (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT,
    autostop_minutes INTEGER DEFAULT -1,
    autostop_down INTEGER DEFAULT 0,
    owner TEXT,
    cluster_hash TEXT,
    resources_str TEXT,
    workspace TEXT DEFAULT 'default'
);
CREATE TABLE IF NOT EXISTS cluster_history (
    cluster_hash TEXT,
    name TEXT,
    launched_at INTEGER,
    duration INTEGER,
    resources_str TEXT,
    num_nodes INTEGER,
    cost REAL,
    user TEXT,
    last_status TEXT
);
CREATE TABLE IF NOT EXISTS cluster_events (
    cluster_name TEXT,
    timestamp REAL,
    event_type TEXT,
    message TEXT
);
CREATE TABLE IF NOT EXISTS storage (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT
);
CREATE TABLE IF NOT EXISTS volumes (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    config TEXT,
    status TEXT
);
CREATE TABLE IF NOT EXISTS users (
    user_hash TEXT PRIMARY KEY,
    name TEXT,
    created_at INTEGER
);
CREATE TABLE IF NOT EXISTS system_config (
    key TEXT PRIMARY KEY,
    value TEXT
);
"""


@functools.lru_cache(maxsize=None)
def _db_for(path: str) -> db_utils.SQLiteDB:
    return db_utils.open_db(path, _CREATE_SQL)


def _db() -> db_utils.SQLiteDB:
    return _db_for(constants.state_db_path())


# ---------------------------------------------------------------------------
# Clusters
# ---------------------------------------------------------------------------
def add_or_update_cluster(cluster_name: str, cluster_handle: Any,
                          requested_resources: Optional[set] = None,
                          is_launch: bool = True,
                          ready: bool = False) -> None:
    """Reference: global_user_state.add_or_update_cluster (:668)."""
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    handle_blob = pickle.dumps(cluster_handle)
    resources_str = ''
    num_nodes = getattr(cluster_handle, 'launched_nodes', 1)
    launched = getattr(cluster_handle, 'launched_resources', None)
    if launched is not None:
        resources_str = f'{num_nodes}x {launched}'
    now = int(time.time())
    row = _db().query_one('SELECT name, launched_at FROM clusters '
                          'WHERE name=?', (cluster_name,))
    launched_at = now if (row is None or is_launch) else row['launched_at']
    # Owner: the API request's server-derived identity when running in
    # an executor worker; the local OS user otherwise.
    from skypilot_tpu.utils import request_context
    owner = request_context.get_request_user() or common_utils.get_user_hash()
    cluster_hash = owner + '-' + cluster_name
    _db().execute(
        'INSERT INTO clusters (name, launched_at, handle, last_use, status, '
        'owner, cluster_hash, resources_str) '
        'VALUES (?,?,?,?,?,?,?,?) '
        'ON CONFLICT(name) DO UPDATE SET launched_at=excluded.launched_at, '
        'handle=excluded.handle, last_use=excluded.last_use, '
        'status=excluded.status, resources_str=excluded.resources_str',
        (cluster_name, launched_at, handle_blob, str(now), status.value,
         owner, cluster_hash, resources_str))
    add_cluster_event(cluster_name,
                      'launched' if is_launch else 'updated',
                      resources_str)


def update_cluster_handle(cluster_name: str, cluster_handle: Any) -> None:
    _db().execute('UPDATE clusters SET handle=? WHERE name=?',
                  (pickle.dumps(cluster_handle), cluster_name))


def set_cluster_status(cluster_name: str, status: ClusterStatus) -> None:
    _db().execute('UPDATE clusters SET status=? WHERE name=?',
                  (status.value, cluster_name))


def update_last_use(cluster_name: str) -> None:
    _db().execute('UPDATE clusters SET last_use=? WHERE name=?',
                  (str(int(time.time())), cluster_name))


def set_cluster_autostop(cluster_name: str, idle_minutes: int,
                         down: bool) -> None:
    _db().execute(
        'UPDATE clusters SET autostop_minutes=?, autostop_down=? '
        'WHERE name=?', (idle_minutes, int(down), cluster_name))


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    row = get_cluster(cluster_name)
    if row is None:
        return
    if terminate:
        # Record history before deletion.
        duration = int(time.time()) - (row['launched_at'] or 0)
        handle = row['handle']
        cost = 0.0
        try:
            launched = getattr(handle, 'launched_resources', None)
            if launched is not None and launched.cloud is not None:
                cost = launched.get_cost(duration) * getattr(
                    handle, 'launched_nodes', 1)
        except Exception:  # pylint: disable=broad-except
            pass
        _db().execute(
            'INSERT INTO cluster_history (cluster_hash, name, launched_at, '
            'duration, resources_str, num_nodes, cost, user, last_status) '
            'VALUES (?,?,?,?,?,?,?,?,?)',
            (row['cluster_hash'], cluster_name, row['launched_at'], duration,
             row['resources_str'], getattr(handle, 'launched_nodes', 1),
             cost, row['owner'], row['status'].value))
        _db().execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
        _db().execute('DELETE FROM cluster_events WHERE cluster_name=?',
                      (cluster_name,))
    else:
        _db().execute('UPDATE clusters SET status=?, handle=? WHERE name=?',
                      (ClusterStatus.STOPPED.value,
                       pickle.dumps(row['handle']), cluster_name))
        add_cluster_event(cluster_name, 'stopped', '')


def _deserialize(row: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(row)
    if out.get('handle') is not None:
        out['handle'] = pickle.loads(out['handle'])
    if out.get('status') is not None:
        out['status'] = ClusterStatus(out['status'])
    return out


def get_cluster(cluster_name: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM clusters WHERE name=?',
                          (cluster_name,))
    return _deserialize(row) if row else None


def get_clusters() -> List[Dict[str, Any]]:
    rows = _db().query('SELECT * FROM clusters ORDER BY launched_at DESC')
    return [_deserialize(r) for r in rows]


def get_handle_from_cluster_name(cluster_name: str) -> Optional[Any]:
    """Reference: global_user_state.get_handle_from_cluster_name (:1515)."""
    row = get_cluster(cluster_name)
    return row['handle'] if row else None


def get_cluster_status(cluster_name: str) -> Optional[ClusterStatus]:
    row = get_cluster(cluster_name)
    return row['status'] if row else None


def cluster_with_name_exists(cluster_name: str) -> bool:
    return get_cluster(cluster_name) is not None


# ---------------------------------------------------------------------------
# Events / history
# ---------------------------------------------------------------------------
def add_cluster_event(cluster_name: str, event_type: str,
                      message: str) -> None:
    _db().execute(
        'INSERT INTO cluster_events (cluster_name, timestamp, event_type, '
        'message) VALUES (?,?,?,?)',
        (cluster_name, time.time(), event_type, message))


def get_cluster_events(cluster_name: str) -> List[Dict[str, Any]]:
    return _db().query(
        'SELECT * FROM cluster_events WHERE cluster_name=? ORDER BY timestamp',
        (cluster_name,))


def get_cluster_history() -> List[Dict[str, Any]]:
    return _db().query(
        'SELECT * FROM cluster_history ORDER BY launched_at DESC')


# ---------------------------------------------------------------------------
# Storage
# ---------------------------------------------------------------------------
def add_or_update_storage(name: str, handle: Any, status: str) -> None:
    _db().execute(
        'INSERT INTO storage (name, launched_at, handle, last_use, status) '
        'VALUES (?,?,?,?,?) ON CONFLICT(name) DO UPDATE SET '
        'handle=excluded.handle, status=excluded.status, '
        'last_use=excluded.last_use',
        (name, int(time.time()), pickle.dumps(handle),
         str(int(time.time())), status))


def get_storage(name: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM storage WHERE name=?', (name,))
    if row is None:
        return None
    out = dict(row)
    out['handle'] = pickle.loads(out['handle'])
    return out


def get_storage_names() -> List[str]:
    return [r['name'] for r in _db().query('SELECT name FROM storage')]


def remove_storage(name: str) -> None:
    _db().execute('DELETE FROM storage WHERE name=?', (name,))


# ---------------------------------------------------------------------------
# System config (key/value)
# ---------------------------------------------------------------------------
def get_system_config(key: str, default: Optional[str] = None
                      ) -> Optional[str]:
    row = _db().query_one('SELECT value FROM system_config WHERE key=?',
                          (key,))
    return row['value'] if row else default


def set_system_config(key: str, value: str) -> None:
    _db().execute(
        'INSERT INTO system_config (key, value) VALUES (?,?) '
        'ON CONFLICT(key) DO UPDATE SET value=excluded.value', (key, value))


def cluster_status_counts() -> Dict[str, int]:
    """{status: count} without unpickling any handles (metrics path)."""
    rows = _db().query(
        'SELECT status, COUNT(*) AS n FROM clusters GROUP BY status')
    return {r['status'].lower(): int(r['n']) for r in rows if r['status']}
