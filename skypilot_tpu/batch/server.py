"""Batch API routes (mounted by server/server.py)."""
from __future__ import annotations

from aiohttp import web

from skypilot_tpu.server.route_utils import scheduled_handler

_API = 'skypilot_tpu.batch.api'


def _schedule(name: str, entrypoint: str, schedule_type: str = 'short'):
    return scheduled_handler(name, entrypoint, schedule_type)


def register(app: web.Application) -> None:
    app.router.add_post('/batch/launch',
                        _schedule('batch.launch', f'{_API}.launch', 'long'))
    app.router.add_post('/batch/ls',
                        _schedule('batch.ls', f'{_API}.ls'))
    app.router.add_post('/batch/cancel',
                        _schedule('batch.cancel', f'{_API}.cancel'))
