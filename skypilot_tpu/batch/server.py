"""Batch API routes (mounted by server/server.py)."""
from __future__ import annotations

from aiohttp import web

from skypilot_tpu.server.requests import executor

_API = 'skypilot_tpu.batch.api'


def _schedule(name: str, entrypoint: str, schedule_type: str = 'short'):

    async def handler(request: web.Request) -> web.Response:
        payload = await request.json() if request.can_read_body else {}
        request_id = executor.schedule_request(
            name, entrypoint, payload, schedule_type=schedule_type,
            user=request.headers.get('X-Skypilot-User', 'unknown'))
        return web.json_response({'request_id': request_id})

    return handler


def register(app: web.Application) -> None:
    app.router.add_post('/batch/launch',
                        _schedule('batch.launch', f'{_API}.launch', 'long'))
    app.router.add_post('/batch/ls',
                        _schedule('batch.ls', f'{_API}.ls'))
    app.router.add_post('/batch/cancel',
                        _schedule('batch.cancel', f'{_API}.cancel'))
