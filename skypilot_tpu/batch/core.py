"""Batch: map a task over dataset shards on a pool of worker clusters.

Reference: sky/batch/ (coordinator + workers over JSONL on object
storage, README.md:1-35). TPU-native shape: the coordinator is a
controller daemon (like managed jobs); it splits the input JSONL into
shards, provisions a pool of worker clusters, and streams shards
through them — each assignment is one agent job with
SKYPILOT_BATCH_SHARD / SKYPILOT_BATCH_OUTPUT env injected. Failed
shards requeue (bounded retries); workers tear down when the queue
drains.
"""
from __future__ import annotations

import enum
import functools
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.utils import db_utils
from skypilot_tpu.utils import subprocess_utils


class BatchStatus(enum.Enum):
    PENDING = 'PENDING'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (BatchStatus.SUCCEEDED, BatchStatus.FAILED,
                        BatchStatus.CANCELLED)


_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS batch_jobs (
    name TEXT PRIMARY KEY,
    status TEXT,
    task_config TEXT,
    input_path TEXT,
    output_dir TEXT,
    num_workers INTEGER,
    num_shards INTEGER,
    shards_done INTEGER DEFAULT 0,
    shards_failed INTEGER DEFAULT 0,
    controller_pid INTEGER DEFAULT -1,
    created_at REAL,
    log_path TEXT
);
"""


@functools.lru_cache(maxsize=None)
def _db_for(path: str) -> db_utils.SQLiteDB:
    return db_utils.open_db(path, _CREATE_SQL)


def _db() -> db_utils.SQLiteDB:
    return _db_for(os.path.join(constants.sky_home(), 'batch.db'))


def split_jsonl(input_path: str, shard_dir: str,
                num_shards: int) -> List[str]:
    """Round-robin split of a JSONL file into shard files."""
    input_path = os.path.expanduser(input_path)
    os.makedirs(shard_dir, exist_ok=True)
    paths = [os.path.join(shard_dir, f'shard-{i:05d}.jsonl')
             for i in range(num_shards)]
    files = [open(p, 'w', encoding='utf-8') for p in paths]
    try:
        with open(input_path, 'r', encoding='utf-8') as f:
            for i, line in enumerate(f):
                if line.strip():
                    files[i % num_shards].write(line)
    finally:
        for f in files:
            f.close()
    return paths


def launch(task_config: Dict[str, Any], name: str, input_path: str,
           output_dir: str, num_workers: int = 2,
           num_shards: Optional[int] = None,
           user: str = 'unknown') -> Dict[str, Any]:
    """Register the batch job and spawn its coordinator daemon."""
    if _db().query_one('SELECT name FROM batch_jobs WHERE name=?',
                       (name,)) is not None:
        raise exceptions.SkyError(f'Batch job {name!r} already exists.')
    num_shards = num_shards or num_workers * 4
    log_dir = os.path.join(constants.sky_home(), 'batch_logs')
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f'{name}.log')
    _db().execute(
        'INSERT INTO batch_jobs (name, status, task_config, input_path, '
        'output_dir, num_workers, num_shards, created_at, log_path) '
        'VALUES (?,?,?,?,?,?,?,?,?)',
        (name, BatchStatus.PENDING.value, json.dumps(task_config),
         input_path, output_dir, num_workers, num_shards, time.time(),
         log_path))
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f'{repo_root}:{env.get("PYTHONPATH", "")}'
    pid = subprocess_utils.launch_daemon(
        [sys.executable, '-m', 'skypilot_tpu.batch.coordinator',
         '--name', name],
        log_path=log_path, env=env)
    _db().execute('UPDATE batch_jobs SET controller_pid=? WHERE name=?',
                  (pid, name))
    del user
    return {'name': name, 'num_shards': num_shards,
            'num_workers': num_workers}


def get(name: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM batch_jobs WHERE name=?', (name,))
    if row is None:
        return None
    out = dict(row)
    out['status'] = BatchStatus(out['status'])
    out['task_config'] = json.loads(out['task_config'] or '{}')
    return out


def ls() -> List[Dict[str, Any]]:
    rows = _db().query('SELECT name, status, num_shards, shards_done, '
                       'shards_failed, num_workers, created_at '
                       'FROM batch_jobs ORDER BY created_at')
    return [dict(r) for r in rows]


def cancel(name: str) -> bool:
    row = get(name)
    if row is None or row['status'].is_terminal():
        return False
    pid = row.get('controller_pid') or -1
    set_status(name, BatchStatus.CANCELLED)
    if pid > 0:
        import signal
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
    return True


def set_status(name: str, status: BatchStatus) -> None:
    _db().execute('UPDATE batch_jobs SET status=? WHERE name=?',
                  (status.value, name))


def set_progress(name: str, done: int, failed: int) -> None:
    _db().execute('UPDATE batch_jobs SET shards_done=?, shards_failed=? '
                  'WHERE name=?', (done, failed, name))
