"""Batch coordinator daemon: shard queue → worker-cluster pool.

Reference: sky/batch/coordinator.py. One process per batch job:
provisions `num_workers` clusters, then streams shards through them —
each assignment submits an agent job on a free worker with the shard
env injected; failures requeue (up to _MAX_SHARD_RETRIES); workers are
torn down when the queue drains.
"""
from __future__ import annotations

import argparse
import os
import queue
import signal
import threading
import time
import traceback
from typing import Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu import execution
from skypilot_tpu import global_state
from skypilot_tpu import task as task_lib
from skypilot_tpu.agent import job_lib as agent_job_lib
from skypilot_tpu.batch import core
from skypilot_tpu.utils import ux_utils

_MAX_SHARD_RETRIES = 2


class Coordinator:

    def __init__(self, name: str) -> None:
        record = core.get(name)
        assert record is not None, name
        self.name = name
        self.record = record
        self.task_config = record['task_config']
        self.cancelled = threading.Event()
        signal.signal(signal.SIGTERM,
                      lambda *a: self.cancelled.set())
        self.shard_queue: 'queue.Queue' = queue.Queue()
        self.done = 0
        self.failed_shards: List[str] = []
        self.lock = threading.Lock()

    def _worker_cluster(self, idx: int) -> str:
        return f'batch-{self.name}-w{idx}'

    # ------------------------------------------------------------------
    def run(self) -> core.BatchStatus:
        record = self.record
        shard_dir = os.path.join(constants.sky_home(), 'batch_shards',
                                 self.name)
        shards = core.split_jsonl(record['input_path'], shard_dir,
                                  record['num_shards'])
        os.makedirs(os.path.expanduser(record['output_dir']), exist_ok=True)
        for shard in shards:
            self.shard_queue.put((shard, 0))
        core.set_status(self.name, core.BatchStatus.RUNNING)

        num_workers = min(record['num_workers'], len(shards))
        threads = []
        for idx in range(num_workers):
            t = threading.Thread(target=self._worker_loop, args=(idx,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

        self._teardown_workers(num_workers)
        if self.cancelled.is_set():
            final = core.BatchStatus.CANCELLED
        elif self.failed_shards:
            final = core.BatchStatus.FAILED
        else:
            final = core.BatchStatus.SUCCEEDED
        core.set_status(self.name, final)
        ux_utils.log(f'Batch {self.name}: {final.value} '
                     f'({self.done}/{len(shards)} shards).')
        return final

    # ------------------------------------------------------------------
    def _worker_loop(self, idx: int) -> None:
        cluster = self._worker_cluster(idx)
        launched = False
        while not self.cancelled.is_set():
            try:
                shard, attempt = self.shard_queue.get_nowait()
            except queue.Empty:
                return
            try:
                if not launched:
                    # First assignment provisions the worker (with the
                    # first shard's env — launch = provision+exec).
                    launched = True
                rc = self._run_shard(cluster, shard)
                if rc:
                    with self.lock:
                        self.done += 1
                else:
                    raise RuntimeError(f'shard failed: {shard}')
            except Exception as e:  # pylint: disable=broad-except
                ux_utils.error(f'Batch {self.name} worker {idx}: {e}')
                if attempt + 1 <= _MAX_SHARD_RETRIES:
                    self.shard_queue.put((shard, attempt + 1))
                else:
                    with self.lock:
                        self.failed_shards.append(shard)
            finally:
                with self.lock:
                    core.set_progress(self.name, self.done,
                                      len(self.failed_shards))
                self.shard_queue.task_done()

    def _run_shard(self, cluster: str, shard: str) -> bool:
        out_path = os.path.join(
            os.path.expanduser(self.record['output_dir']),
            os.path.basename(shard).replace('shard-', 'out-'))
        task = task_lib.Task.from_yaml_config(dict(self.task_config))
        task.update_envs({
            'SKYPILOT_BATCH_SHARD': shard,
            'SKYPILOT_BATCH_OUTPUT': out_path,
            'SKYPILOT_BATCH_NAME': self.name,
        })
        job_id, handle = execution.launch(task, cluster_name=cluster,
                                          detach_run=True,
                                          _quiet_optimizer=True)
        assert job_id is not None and handle is not None
        status = handle.agent().wait_job(job_id)
        return status == agent_job_lib.JobStatus.SUCCEEDED

    def _teardown_workers(self, num_workers: int) -> None:
        from skypilot_tpu import core as sky_core
        for idx in range(num_workers):
            cluster = self._worker_cluster(idx)
            if global_state.get_cluster(cluster) is not None:
                try:
                    sky_core.down(cluster)
                except Exception:  # pylint: disable=broad-except
                    traceback.print_exc()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--name', required=True)
    args = parser.parse_args()
    coordinator = Coordinator(args.name)
    final = coordinator.run()
    raise SystemExit(0 if final == core.BatchStatus.SUCCEEDED else 1)


if __name__ == '__main__':
    main()
