"""Batch request entrypoints (JSON-payload wrappers)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu.batch import core


def launch(task_config: Dict[str, Any], name: str, input_path: str,
           output_dir: str, num_workers: int = 2,
           num_shards: Optional[int] = None,
           user: str = 'unknown') -> Dict[str, Any]:
    return core.launch(task_config, name, input_path, output_dir,
                       num_workers, num_shards, user)


def ls() -> List[Dict[str, Any]]:
    return core.ls()


def cancel(name: str) -> bool:
    return core.cancel(name)
