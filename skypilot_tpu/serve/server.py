"""Serve API routes (mounted by server/server.py).

Reference: sky/serve/server/ (REST under /serve/*).
"""
from __future__ import annotations

from aiohttp import web

from skypilot_tpu.agent import log_lib
from skypilot_tpu.server.route_utils import scheduled_handler, stream_lines

_API = 'skypilot_tpu.serve.core'


def _schedule(name: str, entrypoint: str, schedule_type: str = 'long'):
    return scheduled_handler(name, entrypoint, schedule_type)


async def serve_logs(request: web.Request) -> web.StreamResponse:
    """Stream a service's controller log (reference: `sky serve logs`)."""
    from skypilot_tpu.serve import serve_state
    name = request.query.get('service', '')
    follow = request.query.get('follow', '1') == '1'
    record = serve_state.get_service(name)
    if record is None or not record.get('log_path'):
        return web.json_response({'error': f'no service {name}'},
                                 status=404)

    def finished() -> bool:
        rec = serve_state.get_service(name)
        return rec is None or rec['status'].is_terminal()

    return await stream_lines(
        request,
        lambda: log_lib.tail_logs(record['log_path'], follow=follow,
                                  stop_condition=finished))


def register(app: web.Application) -> None:
    app.router.add_post('/serve/up',
                        _schedule('serve.up', f'{_API}.up'))
    app.router.add_post('/serve/update',
                        _schedule('serve.update', f'{_API}.update'))
    app.router.add_post('/serve/status',
                        _schedule('serve.status', f'{_API}.status', 'short'))
    app.router.add_post('/serve/down',
                        _schedule('serve.down', f'{_API}.down'))
    app.router.add_get('/serve/logs', serve_logs)
