"""Serve API routes (mounted by server/server.py).

Reference: sky/serve/server/ (REST under /serve/*).
"""
from __future__ import annotations

from aiohttp import web

from skypilot_tpu.server.route_utils import scheduled_handler

_API = 'skypilot_tpu.serve.core'


def _schedule(name: str, entrypoint: str, schedule_type: str = 'long'):
    return scheduled_handler(name, entrypoint, schedule_type)


def register(app: web.Application) -> None:
    app.router.add_post('/serve/up',
                        _schedule('serve.up', f'{_API}.up'))
    app.router.add_post('/serve/update',
                        _schedule('serve.update', f'{_API}.update'))
    app.router.add_post('/serve/status',
                        _schedule('serve.status', f'{_API}.status', 'short'))
    app.router.add_post('/serve/down',
                        _schedule('serve.down', f'{_API}.down'))
