"""Service spec: the `service:` section of a task YAML.

Reference: sky/serve/service_spec.py (735 LoC) — readiness probe,
replica policy (min/max, target qps), rolling-update knobs.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from skypilot_tpu import exceptions


class SkyServiceSpec:

    def __init__(self,
                 readiness_path: str = '/',
                 initial_delay_seconds: int = 60,
                 readiness_timeout_seconds: int = 15,
                 post_data: Optional[Any] = None,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 # float (uniform fleet) or {accelerator: qps} dict —
                 # the dict selects the instance-aware autoscaler
                 # (reference: sky/serve/autoscalers.py:605).
                 target_qps_per_replica: Optional[Any] = None,
                 upscale_delay_seconds: int = 60,
                 downscale_delay_seconds: int = 120,
                 port: Optional[int] = None,
                 load_balancing_policy: str = 'round_robin',
                 autoscaler: str = 'request_rate',
                 base_ondemand_fallback_replicas: int = 0,
                 dynamic_ondemand_fallback: bool = False,
                 target_queue_per_replica: float = 4.0) -> None:
        self.autoscaler = autoscaler
        # Spot serving (reference: autoscalers.py:933 fallback logic):
        # keep N always-on-demand replicas, and optionally back-fill
        # preempted spot capacity with on-demand until spot recovers.
        self.base_ondemand_fallback_replicas = base_ondemand_fallback_replicas
        self.dynamic_ondemand_fallback = dynamic_ondemand_fallback
        # queue_length autoscaler target (in-flight requests/replica).
        self.target_queue_per_replica = float(target_queue_per_replica)
        if not readiness_path.startswith('/'):
            raise exceptions.InvalidTaskYAMLError(
                f'readiness path must start with /: {readiness_path!r}')
        self.readiness_path = readiness_path
        self.initial_delay_seconds = initial_delay_seconds
        self.readiness_timeout_seconds = readiness_timeout_seconds
        self.post_data = post_data
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas if max_replicas is not None else \
            min_replicas
        self.target_qps_per_replica = target_qps_per_replica
        self.upscale_delay_seconds = upscale_delay_seconds
        self.downscale_delay_seconds = downscale_delay_seconds
        self.port = port
        self.load_balancing_policy = load_balancing_policy
        if self.max_replicas < self.min_replicas:
            raise exceptions.InvalidTaskYAMLError(
                'max_replicas < min_replicas')
        if isinstance(self.target_qps_per_replica, dict):
            if not self.target_qps_per_replica or any(
                    float(v) <= 0
                    for v in self.target_qps_per_replica.values()):
                raise exceptions.InvalidTaskYAMLError(
                    'target_qps_per_replica accelerator map needs at '
                    'least one entry and all-positive qps values')
        elif (self.target_qps_per_replica is not None and
                self.target_qps_per_replica <= 0):
            raise exceptions.InvalidTaskYAMLError(
                'target_qps_per_replica must be positive')

    @property
    def autoscaling_enabled(self) -> bool:
        return (self.max_replicas > self.min_replicas and
                self.target_qps_per_replica is not None)

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'SkyServiceSpec':
        config = dict(config)
        readiness = config.pop('readiness_probe', '/')
        kwargs: Dict[str, Any] = {}
        if isinstance(readiness, str):
            kwargs['readiness_path'] = readiness
        else:
            readiness = dict(readiness)
            kwargs['readiness_path'] = readiness.pop('path', '/')
            if 'initial_delay_seconds' in readiness:
                kwargs['initial_delay_seconds'] = readiness.pop(
                    'initial_delay_seconds')
            if 'timeout_seconds' in readiness:
                kwargs['readiness_timeout_seconds'] = readiness.pop(
                    'timeout_seconds')
            if 'post_data' in readiness:
                kwargs['post_data'] = readiness.pop('post_data')
            if readiness:
                raise exceptions.InvalidTaskYAMLError(
                    f'Unknown readiness_probe fields: {sorted(readiness)}')
        policy = config.pop('replica_policy', None)
        if policy is None:
            count = config.pop('replicas', 1)
            kwargs['min_replicas'] = kwargs['max_replicas'] = int(count)
        else:
            policy = dict(policy)
            kwargs['min_replicas'] = int(policy.pop('min_replicas', 1))
            if 'max_replicas' in policy:
                kwargs['max_replicas'] = int(policy.pop('max_replicas'))
            if 'target_qps_per_replica' in policy:
                raw = policy.pop('target_qps_per_replica')
                kwargs['target_qps_per_replica'] = (
                    {str(k): float(v) for k, v in raw.items()}
                    if isinstance(raw, dict) else float(raw))
            if 'target_queue_per_replica' in policy:
                kwargs['target_queue_per_replica'] = float(
                    policy.pop('target_queue_per_replica'))
            for key in ('upscale_delay_seconds', 'downscale_delay_seconds',
                        'base_ondemand_fallback_replicas'):
                if key in policy:
                    kwargs[key] = int(policy.pop(key))
            if 'dynamic_ondemand_fallback' in policy:
                kwargs['dynamic_ondemand_fallback'] = bool(
                    policy.pop('dynamic_ondemand_fallback'))
            if policy:
                raise exceptions.InvalidTaskYAMLError(
                    f'Unknown replica_policy fields: {sorted(policy)}')
        if 'port' in config:
            kwargs['port'] = int(config.pop('port'))
        if 'load_balancing_policy' in config:
            kwargs['load_balancing_policy'] = config.pop(
                'load_balancing_policy')
        if 'autoscaler' in config:
            kwargs['autoscaler'] = str(config.pop('autoscaler')).lower()
        if config:
            raise exceptions.InvalidTaskYAMLError(
                f'Unknown service fields: {sorted(config)}')
        return cls(**kwargs)

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            'readiness_probe': {
                'path': self.readiness_path,
                'initial_delay_seconds': self.initial_delay_seconds,
                'timeout_seconds': self.readiness_timeout_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
                'max_replicas': self.max_replicas,
            },
        }
        if self.post_data is not None:
            out['readiness_probe']['post_data'] = self.post_data
        if self.target_qps_per_replica is not None:
            out['replica_policy']['target_qps_per_replica'] = \
                self.target_qps_per_replica
            out['replica_policy']['upscale_delay_seconds'] = \
                self.upscale_delay_seconds
            out['replica_policy']['downscale_delay_seconds'] = \
                self.downscale_delay_seconds
        if self.port is not None:
            out['port'] = self.port
        if self.load_balancing_policy != 'round_robin':
            out['load_balancing_policy'] = self.load_balancing_policy
        if self.autoscaler != 'request_rate':
            out['autoscaler'] = self.autoscaler
        if self.base_ondemand_fallback_replicas:
            out['replica_policy']['base_ondemand_fallback_replicas'] = \
                self.base_ondemand_fallback_replicas
        if self.dynamic_ondemand_fallback:
            out['replica_policy']['dynamic_ondemand_fallback'] = True
        return out
