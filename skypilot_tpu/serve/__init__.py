"""SkyServe-equivalent: multi-replica serving with autoscaling
(reference: sky/serve/)."""
from skypilot_tpu.serve.service_spec import SkyServiceSpec

__all__ = ['SkyServiceSpec']
