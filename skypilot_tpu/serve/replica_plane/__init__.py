"""Multi-replica serving plane: local replica manager, engine-metrics
autoscaling, prefix-affinity load balancing, drain-before-kill.

The serve controller (serve/service.py) orchestrates replicas as
CLUSTERS — launch/terminate through the provisioning stack, probe
readiness over HTTP. This package is the layer below it for the
single-host / local-fleet case the paper's serving benchmarks run:
REAL `serve_lm` server processes on distinct ports of this machine,
scraped and routed directly:

  - replica_manager.py: spawns/terminates serve_lm processes, scrapes
    each replica's `/stats` + `/readyz` on an interval into shared
    `ReplicaView`s, and executes the drain-before-kill contract
    (mark not-ready -> stop routing -> SIGTERM -> wait for the
    replica's own /readyz drain -> only then kill);
  - fleet.py: the control loop wiring scraped engine signals into an
    `EngineMetricsAutoscaler` (serve/autoscalers.py) and the routing
    set into a load-balancing policy;
  - lb.py: a streaming HTTP front-end routing /generate* and /v1/*
    by prefix-cache chain-key affinity
    (serve/load_balancing_policies.py PrefixAffinityPolicy,
    inference/affinity.py), retrying idempotent not-yet-streamed
    requests on replica death;
  - stub.py: a model-free replica speaking the same control surface
    (readyz/stats/generate+SSE, SIGTERM drain, prefix-cache
    accounting) for deterministic tier-1 tests and bench smokes;
  - journal.py: the durable fleet journal (fsync'd JSONL of replica
    lifecycle events, atomic-rename compaction) that makes the
    control plane crash-only — a restarted controller replays it
    and `ReplicaManager.adopt()` reattaches every replica it can
    verify (pid alive + /stats echoing the journaled instance UUID)
    instead of orphaning or killing them.

Entry point: `python -m skypilot_tpu.recipes.serve_fleet`.
"""
from skypilot_tpu.serve.replica_plane.fleet import FleetController
from skypilot_tpu.serve.replica_plane.journal import (FleetJournal,
                                                      ReplicaRecord)
from skypilot_tpu.serve.replica_plane.lb import (PrefillPool,
                                                 make_lb_server)
from skypilot_tpu.serve.replica_plane.replica_manager import (
    ReplicaManager, ReplicaView, serve_lm_factory, stub_factory)

__all__ = ['FleetController', 'FleetJournal', 'PrefillPool',
           'ReplicaManager', 'ReplicaRecord', 'ReplicaView',
           'make_lb_server', 'serve_lm_factory', 'stub_factory']
