"""Stub replica: serve_lm's control surface without the model.

Speaks exactly the subset of the inference server the replica plane
depends on — `GET /readyz` (503 while draining), `GET /healthz`,
`GET /stats` (queued / prefill_backlog_tokens / requests_shed /
prefix_cache), `POST /generate` with SSE streaming, and the SIGTERM
drain contract (readyz flips 503, in-flight requests finish, process
exits 0) — with real prefix-cache accounting: prompts are paged with
the SAME chain-key hash the engine uses (inference/affinity.py), hit
against a bounded per-replica LRU. Affinity routing therefore wins
measurably on stubs for the same reason it wins on real replicas:
pinning a prefix group to one replica stops every replica from
paying (and caching) the same pages.

Chaos knob: `--die-after-tokens K` crashes the process (exit 1) the
moment its K-th token is emitted — a replica death mid-stream, with
deterministic timing. Tier-1 chaos tests run the whole
kill -> reroute -> replace -> no-extra-5xx loop on stubs; the slow
e2e repeats it on real serve_lm processes.

Run as a process: `python -m skypilot_tpu.serve.replica_plane.stub
--port 0 --seed 3`. In-process (tests): `in_process_stub_factory()`
returns a ReplicaManager-compatible factory whose handles expose
`poll/send_signal/kill/wait` plus a `.die()` crash helper.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import signal
import threading
import time
import uuid as uuid_lib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.inference import affinity
from skypilot_tpu.observability import tracing


class _StubDied(Exception):
    """Raised inside a handler to abort its stream when the stub
    'crashes' (in-process mode; subprocess mode just _exits)."""


class StubState:
    """Shared state of one stub replica (thread-safe via `lock`)."""

    def __init__(self, *, seed: int, page_size: int, cache_pages: int,
                 token_sleep_s: float, die_after_tokens: int,
                 on_die: Optional[Callable[[], None]],
                 instance_uuid: Optional[str] = None,
                 role: str = '',
                 prefill_ms_per_token: float = 0.0) -> None:
        self.seed = seed
        # Disaggregation model (mirrors serve_lm --role): one
        # "engine" lock serializes prefill chunks and token emission
        # — a long prompt's simulated prefill delays every other
        # stream's tokens exactly like the real single-engine
        # replica, UNLESS the pages arrived via /kv/import (cache
        # hits cost no prefill). prefill stubs hand the chain keys
        # off to a decode peer and proxy its response.
        self.role = role
        self.prefill_ms_per_token = prefill_ms_per_token
        self.engine_lock = threading.Lock()
        self.decode_peers: List[str] = []
        self.handoffs = 0
        self.handoff_failures = 0
        self.kv_imports = 0
        # Identity echoed in /stats; the replica plane's adoption
        # path matches it against the journaled UUID (same contract
        # as the real serve_lm server).
        self.instance_uuid = (
            instance_uuid or
            os.environ.get('STPU_REPLICA_INSTANCE_UUID') or
            uuid_lib.uuid4().hex)
        self.page_size = page_size
        self.cache_pages = cache_pages
        self.token_sleep_s = token_sleep_s
        self.die_after_tokens = die_after_tokens
        self.on_die = on_die
        self.lock = threading.Lock()
        self.draining = threading.Event()
        self.aborted = threading.Event()
        self.inflight = 0
        self.tokens_emitted = 0
        self.requests_served = 0
        # Prefix "page cache": chain key -> None, LRU order, bounded
        # like the real page pool (evictions make duplicated prefixes
        # expensive, exactly the pressure affinity routing removes).
        self.cache: 'collections.OrderedDict[bytes, None]' = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Engine-side inter-token gaps (seconds), per stream row —
        # the commit-time ITL signal the real engine reports; client
        # SSE timing rides TCP buffering and can't see ms-scale
        # contention. /stats ships the recent raw gaps so a bench
        # can compute true fleet-wide percentiles.
        self.itl_gaps: 'collections.deque' = collections.deque(
            maxlen=4096)
        # Tests inject autoscaler pressure here (merged last into
        # /stats): e.g. {'prefill_backlog_tokens': 99999}.
        self.stats_overrides: Dict[str, Any] = {}

    def account_pages(self, tokens: List[int]) -> int:
        """Record the prompt's chain keys against the bounded page
        cache; returns the number of MISSED pages (the pages this
        replica would have to prefill — imported/cached pages cost
        nothing, which is exactly the disaggregation win)."""
        keys = affinity.chain_keys(tokens, self.page_size)
        n_miss = 0
        with self.lock:
            for key in keys:
                if key in self.cache:
                    self.cache.move_to_end(key)
                    self.hits += 1
                else:
                    self.cache[key] = None
                    self.misses += 1
                    n_miss += 1
                    while len(self.cache) > self.cache_pages:
                        self.cache.popitem(last=False)
                        self.evictions += 1
        return n_miss

    def import_keys(self, keys: List[bytes]) -> int:
        """Decode side of a stub handoff: adopt the chain keys as
        resident pages (no hit/miss accounting — the import is the
        transfer, not a lookup)."""
        n = 0
        with self.lock:
            for key in keys:
                if key not in self.cache:
                    self.cache[key] = None
                    n += 1
                self.cache.move_to_end(key)
                while len(self.cache) > self.cache_pages:
                    self.cache.popitem(last=False)
                    self.evictions += 1
            self.kv_imports += 1
        return n

    def simulate_prefill(self, n_miss_pages: int) -> None:
        """Model compute-bound prefill: one engine-lock hold per
        missed page (chunked prefill — decode tokens of OTHER
        streams interleave between chunks but wait out the chunk in
        flight, like the real scheduler)."""
        if self.prefill_ms_per_token <= 0:
            return
        per_page_s = self.prefill_ms_per_token * self.page_size / 1e3
        for _ in range(n_miss_pages):
            with self.engine_lock:
                time.sleep(per_page_s)

    def emit_token(self) -> None:
        """One token committed; fires the crash knob exactly at the
        configured count."""
        if self.aborted.is_set():
            raise _StubDied()
        with self.lock:
            self.tokens_emitted += 1
            fire = (self.die_after_tokens > 0 and
                    self.tokens_emitted == self.die_after_tokens)
        if fire:
            self.aborted.set()
            if self.on_die is not None:
                self.on_die()
                raise _StubDied()
            os._exit(1)
        if self.token_sleep_s > 0:
            # Decode rides the same engine lock as prefill chunks:
            # a concurrent long prefill stretches THIS stream's
            # inter-token gaps (the unified-replica tail damage the
            # disaggregated arm removes).
            with self.engine_lock:
                time.sleep(self.token_sleep_s)

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            body = {
                'engine': 'stub',
                'instance_uuid': self.instance_uuid,
                'pid': os.getpid(),
                'healthy': not self.aborted.is_set(),
                'role': self.role,
                'queued': self.inflight,
                'prefill_backlog_tokens': 0,
                'requests_shed': 0,
                'requests_served': self.requests_served,
                'tokens_emitted': self.tokens_emitted,
                'handoff': {
                    'decode_peers': list(self.decode_peers),
                    'handoffs': self.handoffs,
                    'failures': self.handoff_failures,
                    'kv_imports': self.kv_imports,
                },
                'itl_gaps_ms': [round(g * 1000.0, 3)
                                for g in self.itl_gaps],
                'prefix_cache': {
                    'hits': self.hits,
                    'misses': self.misses,
                    'hit_rate': round(
                        self.hits / max(self.hits + self.misses, 1),
                        4),
                    'evictions': self.evictions,
                },
            }
            body.update(self.stats_overrides)
        return body


def make_stub_server(port: int, *, seed: int = 0, page_size: int = 16,
                     cache_pages: int = 64,
                     token_sleep_s: float = 0.0,
                     die_after_tokens: int = 0,
                     on_die: Optional[Callable[[], None]] = None,
                     instance_uuid: Optional[str] = None,
                     role: str = '',
                     prefill_ms_per_token: float = 0.0
                     ) -> ThreadingHTTPServer:
    state = StubState(seed=seed, page_size=page_size,
                      cache_pages=cache_pages,
                      token_sleep_s=token_sleep_s,
                      die_after_tokens=die_after_tokens,
                      on_die=on_die, instance_uuid=instance_uuid,
                      role=role,
                      prefill_ms_per_token=prefill_ms_per_token)

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            if self.path == '/healthz':
                self._json({'status': 'alive'})
                return
            if self.path == '/readyz':
                reasons = []
                if state.draining.is_set():
                    reasons.append('draining')
                if state.aborted.is_set():
                    reasons.append('engine dead')
                self._json({'ready': not reasons, 'reasons': reasons},
                           200 if not reasons else 503)
                return
            if self.path in ('/stats', '/v1/stats'):
                self._json(state.stats())
                return
            if self.path.startswith('/debug/trace/'):
                trace_id = self.path.rsplit('/', 1)[-1]
                trace = tracing.get_trace(trace_id)
                if trace is None:
                    self._json({'error': f'unknown trace {trace_id}'},
                               404)
                else:
                    self._json(trace)
                return
            self._json({'status': 'ok', 'model': 'stub',
                        'vocab_size': 50000, 'max_total_len': 4096})

        def do_POST(self):  # noqa: N802
            if self.path == '/kv/peers':
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                with state.lock:
                    state.decode_peers = [
                        str(p) for p in (req.get('decode') or [])]
                self._json({'decode': state.decode_peers})
                return
            if self.path not in ('/generate', '/v1/generate',
                                 '/kv/import'):
                self._json({'error': 'stub serves POST /generate'},
                           404)
                return
            with state.lock:
                state.inflight += 1
            try:
                # Adopt the caller's trace (LB header or a prefill
                # peer's handoff POST): the stub never head-samples —
                # in a fleet the LB owns that decision. Role-tagged
                # process rows make the merged trace read
                # lb -> prefill -> decode.
                ctx = tracing.parse_header(
                    self.headers.get(tracing.HEADER))
                with tracing.span('replica.request', ctx,
                                  process=state.role or 'replica',
                                  path=self.path) as root:
                    self._trace_ctx = root.ctx
                    if self.path == '/kv/import':
                        self._kv_import()
                    else:
                        self._generate()
            except _StubDied:
                # Crash simulation: the connection just breaks —
                # the client sees a reset/truncation, as with a
                # killed process.
                self.close_connection = True
            finally:
                with state.lock:
                    state.inflight -= 1
                    state.requests_served += 1

        def _kv_import(self):
            """Decode side of a stub handoff: adopt the chain keys
            (imported pages = resident pages, no prefill cost), then
            serve the embedded request like a direct /generate."""
            length = int(self.headers.get('Content-Length', 0))
            req = json.loads(self.rfile.read(length))
            keys = [bytes.fromhex(k) for k in (req.get('keys') or [])]
            state.import_keys(keys)
            inner = req.get('request')
            if not inner:
                self._json({'imported': len(keys)})
                return
            self._generate(inner)

        def _handoff(self, req, rows) -> bool:
            """Prefill-role stub: pay the prefill locally, ship the
            chain keys to the first decode peer, proxy its response.
            False on any failure — the caller serves locally (same
            graceful-fallback contract as the real server)."""
            with state.lock:
                peers = list(state.decode_peers)
            if not peers or len(rows) != 1:
                return False
            row = [int(t) for t in rows[0]]
            n_miss = state.account_pages(row)
            state.simulate_prefill(n_miss)
            keys = affinity.chain_keys(row, state.page_size)
            import requests as requests_lib
            key = affinity.token_affinity_key(row, state.page_size)
            peer = peers[0]
            if key is not None and len(peers) > 1:
                idx = int.from_bytes(bytes.fromhex(key)[:4], 'big')
                peer = peers[idx % len(peers)]
            ctx = getattr(self, '_trace_ctx', None)
            hdrs = ({tracing.HEADER: tracing.format_header(ctx)}
                    if ctx is not None else None)
            try:
                with tracing.span('kv.post', ctx, peer=peer,
                                  pages=len(keys)):
                    upstream = requests_lib.post(
                        f'http://{peer}/kv/import',
                        json={'keys': [k.hex() for k in keys],
                              'request': req},
                        headers=hdrs,
                        stream=True, timeout=(2.0, 600.0))
                if upstream.status_code >= 429:
                    upstream.close()
                    raise RuntimeError(
                        f'decode stub answered '
                        f'{upstream.status_code}')
            except (requests_lib.RequestException,
                    RuntimeError) as e:
                with state.lock:
                    state.handoffs += 1
                    state.handoff_failures += 1
                print(f'stub handoff failed ({e}); serving locally',
                      flush=True)
                return False
            with state.lock:
                state.handoffs += 1
            with upstream:
                self.send_response(upstream.status_code)
                ctype = upstream.headers.get('Content-Type',
                                             'application/json')
                self.send_header('Content-Type', ctype)
                body_bytes = None
                if 'text/event-stream' not in ctype:
                    body_bytes = upstream.content
                    self.send_header('Content-Length',
                                     str(len(body_bytes)))
                self.end_headers()
                if body_bytes is not None:
                    self.wfile.write(body_bytes)
                    return True
                try:
                    for chunk in upstream.iter_content(2048):
                        if chunk:
                            self.wfile.write(chunk)
                            self.wfile.flush()
                except (requests_lib.RequestException, OSError):
                    pass  # truncation: same as a replica death
            return True

        def _generate(self, req=None):
            if req is None:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
            rows = req.get('tokens') or [[]]
            if rows and not isinstance(rows[0], list):
                rows = [rows]
            max_new = int(req.get('max_new_tokens', 8))
            stream = bool(req.get('stream'))
            if state.role == 'prefill' and self.path != '/kv/import':
                if self._handoff(req, rows):
                    return
            for row in rows:
                n_miss = state.account_pages([int(t) for t in row])
                state.simulate_prefill(n_miss)
            out_rows = []
            if stream:
                self.send_response(200)
                self.send_header('Content-Type', 'text/event-stream')
                self.send_header('Cache-Control', 'no-cache')
                self.send_header('Connection', 'close')
                self.end_headers()
            for i, row in enumerate(rows):
                produced = list(row)
                last_t = None
                for j in range(max_new):
                    tok = (state.seed * 1000003 + len(row) * 31 +
                           j) % 50000
                    state.emit_token()
                    now = time.monotonic()
                    if last_t is not None:
                        with state.lock:
                            state.itl_gaps.append(now - last_t)
                    last_t = now
                    produced.append(tok)
                    if stream:
                        self.wfile.write(
                            b'data: ' +
                            json.dumps({'index': i,
                                        'token': tok}).encode() +
                            b'\n\n')
                        self.wfile.flush()
                out_rows.append(produced)
            if stream:
                self.wfile.write(
                    b'data: ' + json.dumps(
                        {'done': True, 'tokens': out_rows}).encode() +
                    b'\n\n')
                self.wfile.write(b'data: [DONE]\n\n')
                self.wfile.flush()
            else:
                self._json({'tokens': out_rows})

    server = ThreadingHTTPServer(('127.0.0.1', port), Handler)
    server.stub = state  # type: ignore[attr-defined]
    return server


class InProcessStubReplica:
    """Popen-shaped handle over a threaded stub server, so
    ReplicaManager drives in-process stubs exactly like subprocesses
    — deterministically and without per-process interpreter costs in
    tier-1."""

    def __init__(self, port: int, **stub_kwargs: Any) -> None:
        stub_kwargs.setdefault('on_die', self._die_from_handler)
        self.server = make_stub_server(port, **stub_kwargs)
        self.state: StubState = self.server.stub
        self.port = self.server.server_address[1]
        self._rc: Optional[int] = None
        self._rc_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)
        self._thread.start()

    # -- Popen surface ---------------------------------------------------
    def poll(self) -> Optional[int]:
        with self._rc_lock:
            return self._rc

    def send_signal(self, sig: int) -> None:
        if sig != signal.SIGTERM:
            self.kill()
            return
        if self.poll() is not None:
            return
        threading.Thread(target=self._drain, daemon=True).start()

    def terminate(self) -> None:
        self.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        self._stop(-9)

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError('stub did not exit')
            time.sleep(0.01)
        return self.poll()

    # -- crash + drain helpers -------------------------------------------
    def die(self, rc: int = 1) -> None:
        """Abrupt death (test chaos helper): in-flight streams break,
        new connections are refused."""
        self.state.aborted.set()
        self._stop(rc)

    def _die_from_handler(self) -> None:
        # Called from inside a handler thread when die_after_tokens
        # fires: stop the server from ANOTHER thread (shutdown()
        # joins the serve loop) and let the handler raise.
        threading.Thread(target=self._stop, args=(1,),
                         daemon=True).start()

    def _drain(self) -> None:
        """The serve_lm SIGTERM contract: readyz flips 503, in-flight
        requests finish, then exit 0."""
        self.state.draining.set()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with self.state.lock:
                if self.state.inflight == 0:
                    break
            time.sleep(0.02)
        self._stop(0)

    def _stop(self, rc: int) -> None:
        with self._rc_lock:
            if self._rc is not None:
                return
            self._rc = rc
        try:
            self.server.shutdown()
            self.server.server_close()
        except OSError:
            pass  # already closed


def in_process_stub_factory(**stub_kwargs: Any
                            ) -> Callable[[int, int],
                                          InProcessStubReplica]:
    """ReplicaManager factory for in-process stubs.
    `per_replica` (optional: {replica_id: {kwargs}}) overrides knobs
    for specific replicas — e.g. give replica 2 a die_after_tokens."""
    per_replica = stub_kwargs.pop('per_replica', {})

    def spawn(replica_id: int, port: int,
              instance_uuid: str = '',
              role: str = '') -> InProcessStubReplica:
        kwargs = dict(stub_kwargs)
        kwargs.update(per_replica.get(replica_id, {}))
        kwargs.setdefault('seed', replica_id)
        if instance_uuid:
            kwargs.setdefault('instance_uuid', instance_uuid)
        if role:
            kwargs.setdefault('role', role)
        return InProcessStubReplica(port, **kwargs)

    return spawn


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, default=0)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--page-size', type=int, default=16)
    parser.add_argument('--cache-pages', type=int, default=64)
    parser.add_argument('--token-sleep-ms', type=float, default=1.0)
    parser.add_argument('--die-after-tokens', type=int, default=0)
    parser.add_argument('--role', choices=['', 'prefill', 'decode'],
                        default='')
    parser.add_argument('--prefill-ms-per-token', type=float,
                        default=0.0,
                        help='simulated compute-bound prefill: each '
                             'missed prompt page holds the engine '
                             'lock page_size*this ms (decode tokens '
                             'of other streams wait it out, like the '
                             'real chunked-prefill scheduler)')
    args = parser.parse_args()

    server = make_stub_server(
        args.port, seed=args.seed, page_size=args.page_size,
        cache_pages=args.cache_pages,
        token_sleep_s=args.token_sleep_ms / 1000.0,
        die_after_tokens=args.die_after_tokens, on_die=None,
        role=args.role,
        prefill_ms_per_token=args.prefill_ms_per_token)
    state: StubState = server.stub

    def _drain_loop():
        state.draining.set()
        time.sleep(0.2)  # stragglers
        server.shutdown()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with state.lock:
                if state.inflight == 0:
                    break
            time.sleep(0.02)
        os._exit(0)

    _term = threading.Event()
    threading.Thread(target=lambda: (_term.wait(), _drain_loop()),
                     daemon=True).start()
    signal.signal(signal.SIGTERM, lambda *_: _term.set())
    print(f'stub replica listening on '
          f':{server.server_address[1]} seed={args.seed}', flush=True)
    server.serve_forever()


if __name__ == '__main__':
    main()
