"""Stub replica: serve_lm's control surface without the model.

Speaks exactly the subset of the inference server the replica plane
depends on — `GET /readyz` (503 while draining), `GET /healthz`,
`GET /stats` (queued / prefill_backlog_tokens / requests_shed /
prefix_cache), `POST /generate` with SSE streaming, and the SIGTERM
drain contract (readyz flips 503, in-flight requests finish, process
exits 0) — with real prefix-cache accounting: prompts are paged with
the SAME chain-key hash the engine uses (inference/affinity.py), hit
against a bounded per-replica LRU. Affinity routing therefore wins
measurably on stubs for the same reason it wins on real replicas:
pinning a prefix group to one replica stops every replica from
paying (and caching) the same pages.

Chaos knob: `--die-after-tokens K` crashes the process (exit 1) the
moment its K-th token is emitted — a replica death mid-stream, with
deterministic timing. Tier-1 chaos tests run the whole
kill -> reroute -> replace -> no-extra-5xx loop on stubs; the slow
e2e repeats it on real serve_lm processes.

Run as a process: `python -m skypilot_tpu.serve.replica_plane.stub
--port 0 --seed 3`. In-process (tests): `in_process_stub_factory()`
returns a ReplicaManager-compatible factory whose handles expose
`poll/send_signal/kill/wait` plus a `.die()` crash helper.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import signal
import threading
import time
import uuid as uuid_lib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.inference import affinity
from skypilot_tpu.inference import sse
from skypilot_tpu.observability import tracing
from skypilot_tpu.robustness import faults


class _StubDied(Exception):
    """Raised inside a handler to abort its stream when the stub
    'crashes' (in-process mode; subprocess mode just _exits)."""


class StubState:
    """Shared state of one stub replica (thread-safe via `lock`)."""

    def __init__(self, *, seed: int, page_size: int, cache_pages: int,
                 token_sleep_s: float, die_after_tokens: int,
                 on_die: Optional[Callable[[], None]],
                 instance_uuid: Optional[str] = None,
                 role: str = '',
                 prefill_ms_per_token: float = 0.0,
                 zone: str = '',
                 migrate: bool = True) -> None:
        self.seed = seed
        # Spot placement label: echoed in /stats and matched against
        # zone-scoped `serve.preempt_notice` fault rules (the
        # decode_zone_storm plan preempts exactly one zone's pool).
        self.zone = zone
        # Live migration (tentpole): with `migrate` off this stub is
        # the full-replay A/B arm — a preemption just kills it and
        # the client replays the whole prompt elsewhere.
        self.migrate_enabled = migrate
        self.evacuate = threading.Event()
        self.evac_reason = 'drain'
        self.evac_target: Optional[str] = None
        self.evac_budget: Optional[int] = None  # None = all sessions
        self.migrations: Dict[str, int] = {}
        self.migration_failures = 0
        self.sessions_evacuated = 0
        self.chains_evacuated = 0
        self.migrations_in = 0
        self.tokens_recomputed = 0
        self.migrated_in_keys: List[str] = []
        # Disaggregation model (mirrors serve_lm --role): one
        # "engine" lock serializes prefill chunks and token emission
        # — a long prompt's simulated prefill delays every other
        # stream's tokens exactly like the real single-engine
        # replica, UNLESS the pages arrived via /kv/import (cache
        # hits cost no prefill). prefill stubs hand the chain keys
        # off to a decode peer and proxy its response.
        self.role = role
        self.prefill_ms_per_token = prefill_ms_per_token
        self.engine_lock = threading.Lock()
        self.decode_peers: List[str] = []
        self.handoffs = 0
        self.handoff_failures = 0
        self.kv_imports = 0
        # Identity echoed in /stats; the replica plane's adoption
        # path matches it against the journaled UUID (same contract
        # as the real serve_lm server).
        self.instance_uuid = (
            instance_uuid or
            os.environ.get('STPU_REPLICA_INSTANCE_UUID') or
            uuid_lib.uuid4().hex)
        self.page_size = page_size
        self.cache_pages = cache_pages
        self.token_sleep_s = token_sleep_s
        self.die_after_tokens = die_after_tokens
        self.on_die = on_die
        self.lock = threading.Lock()
        self.draining = threading.Event()
        self.aborted = threading.Event()
        self.inflight = 0
        self.tokens_emitted = 0
        self.requests_served = 0
        # Prefix "page cache": chain key -> None, LRU order, bounded
        # like the real page pool (evictions make duplicated prefixes
        # expensive, exactly the pressure affinity routing removes).
        self.cache: 'collections.OrderedDict[bytes, None]' = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Engine-side inter-token gaps (seconds), per stream row —
        # the commit-time ITL signal the real engine reports; client
        # SSE timing rides TCP buffering and can't see ms-scale
        # contention. /stats ships the recent raw gaps so a bench
        # can compute true fleet-wide percentiles.
        self.itl_gaps: 'collections.deque' = collections.deque(
            maxlen=4096)
        # Tests inject autoscaler pressure here (merged last into
        # /stats): e.g. {'prefill_backlog_tokens': 99999}.
        self.stats_overrides: Dict[str, Any] = {}

    def account_pages(self, tokens: List[int]) -> int:
        """Record the prompt's chain keys against the bounded page
        cache; returns the number of MISSED pages (the pages this
        replica would have to prefill — imported/cached pages cost
        nothing, which is exactly the disaggregation win)."""
        keys = affinity.chain_keys(tokens, self.page_size)
        n_miss = 0
        with self.lock:
            for key in keys:
                if key in self.cache:
                    self.cache.move_to_end(key)
                    self.hits += 1
                else:
                    self.cache[key] = None
                    self.misses += 1
                    n_miss += 1
                    while len(self.cache) > self.cache_pages:
                        self.cache.popitem(last=False)
                        self.evictions += 1
        return n_miss

    def begin_evacuation(self, reason: str,
                         target: Optional[str] = None,
                         max_sessions: Optional[int] = None) -> None:
        """Arm evacuation: in-flight streams start migrating out at
        their next token boundary. `max_sessions` bounds how many
        (rebalance); None evacuates everything (drain/preempt)."""
        with self.lock:
            self.evac_reason = reason or 'drain'
            self.evac_target = target or None
            self.evac_budget = (int(max_sessions)
                                if max_sessions is not None else None)
        self.evacuate.set()

    def take_evac_slot(self) -> Optional[tuple]:
        """Claim one evacuation slot: (reason, target) when this
        stream should migrate out now, else None. Bounded
        evacuations hand out `max_sessions` slots then disarm."""
        with self.lock:
            if not self.evacuate.is_set():
                return None
            if self.evac_budget is not None:
                if self.evac_budget <= 0:
                    self.evacuate.clear()
                    return None
                self.evac_budget -= 1
                if self.evac_budget == 0:
                    self.evacuate.clear()
            return self.evac_reason, self.evac_target

    def fully_evacuating(self) -> bool:
        """An unbounded evacuation is in progress (drain/preempt):
        readyz flips 503 so the LB stops sending fresh sessions to a
        replica that is emptying itself."""
        with self.lock:
            return (self.evacuate.is_set() and
                    self.evac_budget is None)

    def import_keys(self, keys: List[bytes]) -> int:
        """Decode side of a stub handoff: adopt the chain keys as
        resident pages (no hit/miss accounting — the import is the
        transfer, not a lookup)."""
        n = 0
        with self.lock:
            for key in keys:
                if key not in self.cache:
                    self.cache[key] = None
                    n += 1
                self.cache.move_to_end(key)
                while len(self.cache) > self.cache_pages:
                    self.cache.popitem(last=False)
                    self.evictions += 1
            self.kv_imports += 1
        return n

    def simulate_prefill(self, n_miss_pages: int) -> None:
        """Model compute-bound prefill: one engine-lock hold per
        missed page (chunked prefill — decode tokens of OTHER
        streams interleave between chunks but wait out the chunk in
        flight, like the real scheduler)."""
        if self.prefill_ms_per_token <= 0:
            return
        per_page_s = self.prefill_ms_per_token * self.page_size / 1e3
        for _ in range(n_miss_pages):
            with self.engine_lock:
                time.sleep(per_page_s)

    def emit_token(self) -> None:
        """One token committed; fires the crash knob exactly at the
        configured count."""
        if self.aborted.is_set():
            raise _StubDied()
        with self.lock:
            self.tokens_emitted += 1
            fire = (self.die_after_tokens > 0 and
                    self.tokens_emitted == self.die_after_tokens)
        if fire:
            self.aborted.set()
            if self.on_die is not None:
                self.on_die()
                raise _StubDied()
            os._exit(1)
        if self.token_sleep_s > 0:
            # Decode rides the same engine lock as prefill chunks:
            # a concurrent long prefill stretches THIS stream's
            # inter-token gaps (the unified-replica tail damage the
            # disaggregated arm removes).
            with self.engine_lock:
                time.sleep(self.token_sleep_s)

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            body = {
                'engine': 'stub',
                'instance_uuid': self.instance_uuid,
                'pid': os.getpid(),
                'healthy': not self.aborted.is_set(),
                'role': self.role,
                'zone': self.zone,
                'queued': self.inflight,
                'prefill_backlog_tokens': 0,
                'requests_shed': 0,
                'requests_served': self.requests_served,
                'tokens_emitted': self.tokens_emitted,
                'handoff': {
                    'decode_peers': list(self.decode_peers),
                    'handoffs': self.handoffs,
                    'failures': self.handoff_failures,
                    'kv_imports': self.kv_imports,
                },
                'itl_gaps_ms': [round(g * 1000.0, 3)
                                for g in self.itl_gaps],
                'prefix_cache': {
                    'hits': self.hits,
                    'misses': self.misses,
                    'hit_rate': round(
                        self.hits / max(self.hits + self.misses, 1),
                        4),
                    'evictions': self.evictions,
                },
            }
            if (self.migrations or self.sessions_evacuated or
                    self.migrations_in or self.migration_failures):
                body['migration'] = {
                    'migrations': dict(self.migrations),
                    'failures': self.migration_failures,
                    'sessions_evacuated': self.sessions_evacuated,
                    'chains_evacuated': self.chains_evacuated,
                    'migrations_in': self.migrations_in,
                    'tokens_recomputed': self.tokens_recomputed,
                    'migrated_in_keys': list(self.migrated_in_keys),
                }
            body.update(self.stats_overrides)
        return body


def make_stub_server(port: int, *, seed: int = 0, page_size: int = 16,
                     cache_pages: int = 64,
                     token_sleep_s: float = 0.0,
                     die_after_tokens: int = 0,
                     on_die: Optional[Callable[[], None]] = None,
                     instance_uuid: Optional[str] = None,
                     role: str = '',
                     prefill_ms_per_token: float = 0.0,
                     zone: str = '',
                     migrate: bool = True
                     ) -> ThreadingHTTPServer:
    state = StubState(seed=seed, page_size=page_size,
                      cache_pages=cache_pages,
                      token_sleep_s=token_sleep_s,
                      die_after_tokens=die_after_tokens,
                      on_die=on_die, instance_uuid=instance_uuid,
                      role=role,
                      prefill_ms_per_token=prefill_ms_per_token,
                      zone=zone, migrate=migrate)

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            if self.path == '/healthz':
                self._json({'status': 'alive'})
                return
            if self.path == '/readyz':
                reasons = []
                if state.draining.is_set():
                    reasons.append('draining')
                if state.aborted.is_set():
                    reasons.append('engine dead')
                if state.fully_evacuating():
                    reasons.append('evacuating')
                self._json({'ready': not reasons, 'reasons': reasons},
                           200 if not reasons else 503)
                return
            if self.path in ('/stats', '/v1/stats'):
                self._json(state.stats())
                return
            if self.path.startswith('/debug/trace/'):
                trace_id = self.path.rsplit('/', 1)[-1]
                trace = tracing.get_trace(trace_id)
                if trace is None:
                    self._json({'error': f'unknown trace {trace_id}'},
                               404)
                else:
                    self._json(trace)
                return
            self._json({'status': 'ok', 'model': 'stub',
                        'vocab_size': 50000, 'max_total_len': 4096})

        def do_POST(self):  # noqa: N802
            if self.path == '/kv/peers':
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                with state.lock:
                    state.decode_peers = [
                        str(p) for p in (req.get('decode') or [])]
                self._json({'decode': state.decode_peers})
                return
            if self.path == '/kv/evacuate':
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length)) \
                    if length else {}
                reason = str(req.get('reason') or 'drain')
                state.begin_evacuation(
                    reason, req.get('target'),
                    req.get('max_sessions'))
                with state.lock:
                    inflight = state.inflight
                self._json({'evacuated': inflight,
                            'chains': inflight, 'queued': 0,
                            'reason': reason})
                return
            if self.path not in ('/generate', '/v1/generate',
                                 '/kv/import', '/kv/migrate'):
                self._json({'error': 'stub serves POST /generate'},
                           404)
                return
            with state.lock:
                state.inflight += 1
            try:
                # Adopt the caller's trace (LB header or a prefill
                # peer's handoff POST): the stub never head-samples —
                # in a fleet the LB owns that decision. Role-tagged
                # process rows make the merged trace read
                # lb -> prefill -> decode.
                ctx = tracing.parse_header(
                    self.headers.get(tracing.HEADER))
                with tracing.span('replica.request', ctx,
                                  process=state.role or 'replica',
                                  path=self.path) as root:
                    self._trace_ctx = root.ctx
                    if self.path == '/kv/import':
                        self._kv_import()
                    elif self.path == '/kv/migrate':
                        self._kv_migrate()
                    else:
                        self._generate()
            except _StubDied:
                # Crash simulation: the connection just breaks —
                # the client sees a reset/truncation, as with a
                # killed process.
                self.close_connection = True
            finally:
                with state.lock:
                    state.inflight -= 1
                    state.requests_served += 1

        def _kv_import(self):
            """Decode side of a stub handoff: adopt the chain keys
            (imported pages = resident pages, no prefill cost), then
            serve the embedded request like a direct /generate."""
            length = int(self.headers.get('Content-Length', 0))
            req = json.loads(self.rfile.read(length))
            keys = [bytes.fromhex(k) for k in (req.get('keys') or [])]
            state.import_keys(keys)
            inner = req.get('request')
            if not inner:
                self._json({'imported': len(keys)})
                return
            self._generate(inner)

        def _kv_migrate(self):
            """Receiving side of a live session migration: adopt the
            shipped chain keys (warm pages — the continuation prefill
            costs only the uncovered tail), account the tokens this
            replica did NOT have to recompute, then continue the
            embedded request exactly where the sender stopped."""
            length = int(self.headers.get('Content-Length', 0))
            req = json.loads(self.rfile.read(length))
            keys = [bytes.fromhex(k) for k in (req.get('keys') or [])]
            state.import_keys(keys)
            inner = req.get('request') or {}
            rows = inner.get('tokens') or [[]]
            row = [int(t) for t in (rows[0] if rows else [])]
            covered = len(keys) * state.page_size
            recomputed = max(0, len(row) - covered)
            key = affinity.token_affinity_key(row, state.page_size)
            with state.lock:
                state.migrations_in += 1
                state.tokens_recomputed += recomputed
                if key is not None:
                    state.migrated_in_keys.append(key)
                    del state.migrated_in_keys[:-1024]
            self._generate(inner)

        def _migrate_out(self, reason: str, target: Optional[str],
                         produced: List[int], base_len: int,
                         gen_seed: int, j_next: int, j_end: int,
                         stream: bool) -> Optional[List[int]]:
            """Ship this stream's committed tokens + chain keys to a
            peer and take over its response: stream mode pipes the
            peer's SSE tail through verbatim (returns []), non-stream
            returns the peer's final full row. None on any failure —
            the caller finishes locally (a migration must never
            become a client error)."""
            with state.lock:
                peers = list(state.decode_peers)
            peer = target
            if peer is None:
                if not peers:
                    return None
                key = affinity.token_affinity_key(produced,
                                                  state.page_size)
                peer = peers[0]
                if key is not None and len(peers) > 1:
                    idx = int.from_bytes(bytes.fromhex(key)[:4],
                                         'big')
                    peer = peers[idx % len(peers)]
            keys = affinity.chain_keys(produced, state.page_size)
            body = {
                'keys': [k.hex() for k in keys],
                'reason': reason,
                'request': {
                    'tokens': [list(produced)],
                    'max_new_tokens': j_end - j_next,
                    'stream': stream,
                    # The receiver re-derives the SAME greedy token
                    # sequence the origin would have produced: token
                    # j of a prompt of base_len under gen_seed, not
                    # its own seed over the longer committed row.
                    '_continuation': {'prompt_len': base_len,
                                      'j_start': j_next,
                                      'seed': gen_seed},
                },
            }
            import requests as requests_lib
            ctx = getattr(self, '_trace_ctx', None)
            hdrs = ({tracing.HEADER: tracing.format_header(ctx)}
                    if ctx is not None else None)
            try:
                with tracing.span('kv.migrate', ctx, peer=peer,
                                  reason=reason, pages=len(keys)):
                    upstream = requests_lib.post(
                        f'http://{peer}/kv/migrate', json=body,
                        headers=hdrs, stream=True,
                        timeout=(2.0, 600.0))
                if upstream.status_code != 200:
                    upstream.close()
                    raise RuntimeError(
                        f'peer answered {upstream.status_code}')
            except (requests_lib.RequestException,
                    RuntimeError) as e:
                with state.lock:
                    state.migration_failures += 1
                print(f'stub: migration to {peer} failed ({e}); '
                      f'finishing locally', flush=True)
                return None
            with state.lock:
                state.migrations[reason] = \
                    state.migrations.get(reason, 0) + 1
                state.sessions_evacuated += 1
                state.chains_evacuated += 1
            with upstream:
                if stream:
                    # Arrival-granular tail piping (sse.pipe): the
                    # client keeps seeing tokens the moment the new
                    # owner emits them; truncation looks like a
                    # replica death and is already logged there.
                    sse.pipe(upstream, self.wfile)
                    return []
                try:
                    rows = upstream.json().get('tokens') or [[]]
                    return [int(t) for t in rows[0]]
                except (ValueError, IndexError) as e:
                    print(f'stub: migrated response unparsable '
                          f'({e}); finishing locally', flush=True)
                    with state.lock:
                        state.migration_failures += 1
                    return None

        def _handoff(self, req, rows) -> bool:
            """Prefill-role stub: pay the prefill locally, ship the
            chain keys to the first decode peer, proxy its response.
            False on any failure — the caller serves locally (same
            graceful-fallback contract as the real server)."""
            with state.lock:
                peers = list(state.decode_peers)
            if not peers or len(rows) != 1:
                return False
            row = [int(t) for t in rows[0]]
            n_miss = state.account_pages(row)
            state.simulate_prefill(n_miss)
            keys = affinity.chain_keys(row, state.page_size)
            import requests as requests_lib
            key = affinity.token_affinity_key(row, state.page_size)
            peer = peers[0]
            if key is not None and len(peers) > 1:
                idx = int.from_bytes(bytes.fromhex(key)[:4], 'big')
                peer = peers[idx % len(peers)]
            ctx = getattr(self, '_trace_ctx', None)
            hdrs = ({tracing.HEADER: tracing.format_header(ctx)}
                    if ctx is not None else None)
            try:
                with tracing.span('kv.post', ctx, peer=peer,
                                  pages=len(keys)):
                    upstream = requests_lib.post(
                        f'http://{peer}/kv/import',
                        json={'keys': [k.hex() for k in keys],
                              'request': req},
                        headers=hdrs,
                        stream=True, timeout=(2.0, 600.0))
                if upstream.status_code >= 429:
                    upstream.close()
                    raise RuntimeError(
                        f'decode stub answered '
                        f'{upstream.status_code}')
            except (requests_lib.RequestException,
                    RuntimeError) as e:
                with state.lock:
                    state.handoffs += 1
                    state.handoff_failures += 1
                print(f'stub handoff failed ({e}); serving locally',
                      flush=True)
                return False
            with state.lock:
                state.handoffs += 1
            with upstream:
                self.send_response(upstream.status_code)
                ctype = upstream.headers.get('Content-Type',
                                             'application/json')
                self.send_header('Content-Type', ctype)
                body_bytes = None
                if 'text/event-stream' not in ctype:
                    body_bytes = upstream.content
                    self.send_header('Content-Length',
                                     str(len(body_bytes)))
                self.end_headers()
                if body_bytes is not None:
                    self.wfile.write(body_bytes)
                    return True
                # Arrival-granular SSE pass-through; truncation is
                # bounded and logged by the pipe itself.
                sse.pipe(upstream, self.wfile)
            return True

        def _generate(self, req=None):
            if req is None:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
            rows = req.get('tokens') or [[]]
            if rows and not isinstance(rows[0], list):
                rows = [rows]
            max_new = int(req.get('max_new_tokens', 8))
            stream = bool(req.get('stream'))
            if state.role == 'prefill' and self.path not in (
                    '/kv/import', '/kv/migrate'):
                if self._handoff(req, rows):
                    return
            for row in rows:
                n_miss = state.account_pages([int(t) for t in row])
                state.simulate_prefill(n_miss)
            out_rows = []
            if stream:
                self.send_response(200)
                self.send_header('Content-Type', 'text/event-stream')
                self.send_header('Cache-Control', 'no-cache')
                self.send_header('Connection', 'close')
                self.end_headers()
            # Migration continuations re-derive the origin's token
            # stream: token j of a base_len prompt under the ORIGIN
            # replica's seed (bit-identity across the migration).
            cont = req.get('_continuation') or {}
            for i, row in enumerate(rows):
                produced = list(row)
                base_len = int(cont.get('prompt_len', len(row)))
                j_start = int(cont.get('j_start', 0))
                gen_seed = int(cont.get('seed', state.seed))
                j_end = j_start + max_new
                last_t = None
                migrate_tried = False
                for j in range(j_start, j_end):
                    if (state.migrate_enabled and len(rows) == 1 and
                            not migrate_tried and
                            state.evacuate.is_set()):
                        slot = state.take_evac_slot()
                        if slot is not None:
                            migrate_tried = True
                            result = self._migrate_out(
                                slot[0], slot[1], produced,
                                base_len, gen_seed, j, j_end,
                                stream)
                            if result is not None:
                                if stream:
                                    return  # peer piped the tail
                                self._json({'tokens': [result]})
                                return
                    tok = (gen_seed * 1000003 + base_len * 31 +
                           j) % 50000
                    state.emit_token()
                    now = time.monotonic()
                    if last_t is not None:
                        with state.lock:
                            state.itl_gaps.append(now - last_t)
                    last_t = now
                    produced.append(tok)
                    if stream:
                        self.wfile.write(
                            b'data: ' +
                            json.dumps({'index': i,
                                        'token': tok}).encode() +
                            b'\n\n')
                        self.wfile.flush()
                out_rows.append(produced)
            if stream:
                self.wfile.write(
                    b'data: ' + json.dumps(
                        {'done': True, 'tokens': out_rows}).encode() +
                    b'\n\n')
                self.wfile.write(b'data: [DONE]\n\n')
                self.wfile.flush()
            else:
                self._json({'tokens': out_rows})

    server = ThreadingHTTPServer(('127.0.0.1', port), Handler)
    server.stub = state  # type: ignore[attr-defined]
    return server


class InProcessStubReplica:
    """Popen-shaped handle over a threaded stub server, so
    ReplicaManager drives in-process stubs exactly like subprocesses
    — deterministically and without per-process interpreter costs in
    tier-1."""

    def __init__(self, port: int, **stub_kwargs: Any) -> None:
        stub_kwargs.setdefault('on_die', self._die_from_handler)
        self.server = make_stub_server(port, **stub_kwargs)
        self.state: StubState = self.server.stub
        self.port = self.server.server_address[1]
        self._rc: Optional[int] = None
        self._rc_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True)
        self._thread.start()

    # -- Popen surface ---------------------------------------------------
    def poll(self) -> Optional[int]:
        with self._rc_lock:
            return self._rc

    def send_signal(self, sig: int) -> None:
        if sig != signal.SIGTERM:
            self.kill()
            return
        if self.poll() is not None:
            return
        threading.Thread(target=self._drain, daemon=True).start()

    def terminate(self) -> None:
        self.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        self._stop(-9)

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError('stub did not exit')
            time.sleep(0.01)
        return self.poll()

    # -- crash + drain helpers -------------------------------------------
    def die(self, rc: int = 1) -> None:
        """Abrupt death (test chaos helper): in-flight streams break,
        new connections are refused."""
        self.state.aborted.set()
        self._stop(rc)

    def _die_from_handler(self) -> None:
        # Called from inside a handler thread when die_after_tokens
        # fires: stop the server from ANOTHER thread (shutdown()
        # joins the serve loop) and let the handler raise.
        threading.Thread(target=self._stop, args=(1,),
                         daemon=True).start()

    def _drain(self) -> None:
        """The serve_lm SIGTERM contract: readyz flips 503, in-flight
        requests finish (migrating out when the controller armed
        evacuation or peers are known), then exit 0."""
        self.state.begin_evacuation('drain')
        self.state.draining.set()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with self.state.lock:
                if self.state.inflight == 0:
                    break
            time.sleep(0.02)
        self._stop(0)

    def _stop(self, rc: int) -> None:
        with self._rc_lock:
            if self._rc is not None:
                return
            self._rc = rc
        try:
            self.server.shutdown()
            self.server.server_close()
        except OSError:
            pass  # already closed


def in_process_stub_factory(**stub_kwargs: Any
                            ) -> Callable[[int, int],
                                          InProcessStubReplica]:
    """ReplicaManager factory for in-process stubs.
    `per_replica` (optional: {replica_id: {kwargs}}) overrides knobs
    for specific replicas — e.g. give replica 2 a die_after_tokens."""
    per_replica = stub_kwargs.pop('per_replica', {})

    def spawn(replica_id: int, port: int,
              instance_uuid: str = '',
              role: str = '',
              zone: str = '') -> InProcessStubReplica:
        kwargs = dict(stub_kwargs)
        kwargs.update(per_replica.get(replica_id, {}))
        kwargs.setdefault('seed', replica_id)
        if instance_uuid:
            kwargs.setdefault('instance_uuid', instance_uuid)
        if role:
            kwargs.setdefault('role', role)
        if zone:
            kwargs.setdefault('zone', zone)
        return InProcessStubReplica(port, **kwargs)

    return spawn


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, default=0)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--page-size', type=int, default=16)
    parser.add_argument('--cache-pages', type=int, default=64)
    parser.add_argument('--token-sleep-ms', type=float, default=1.0)
    parser.add_argument('--die-after-tokens', type=int, default=0)
    parser.add_argument('--role', choices=['', 'prefill', 'decode'],
                        default='')
    parser.add_argument('--zone', default='',
                        help='spot placement label: echoed in /stats '
                             'and matched against zone-scoped '
                             'serve.preempt_notice fault rules')
    parser.add_argument('--no-migrate', action='store_true',
                        help='full-replay A/B arm: a preemption '
                             'kills this stub instead of migrating '
                             'its sessions out')
    parser.add_argument('--prefill-ms-per-token', type=float,
                        default=0.0,
                        help='simulated compute-bound prefill: each '
                             'missed prompt page holds the engine '
                             'lock page_size*this ms (decode tokens '
                             'of other streams wait it out, like the '
                             'real chunked-prefill scheduler)')
    args = parser.parse_args()

    server = make_stub_server(
        args.port, seed=args.seed, page_size=args.page_size,
        cache_pages=args.cache_pages,
        token_sleep_s=args.token_sleep_ms / 1000.0,
        die_after_tokens=args.die_after_tokens, on_die=None,
        role=args.role,
        prefill_ms_per_token=args.prefill_ms_per_token,
        zone=args.zone, migrate=not args.no_migrate)
    state: StubState = server.stub

    def _drain_loop():
        state.begin_evacuation('drain')
        state.draining.set()
        time.sleep(0.2)  # stragglers
        server.shutdown()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with state.lock:
                if state.inflight == 0:
                    break
            time.sleep(0.02)
        os._exit(0)

    _term = threading.Event()
    threading.Thread(target=lambda: (_term.wait(), _drain_loop()),
                     daemon=True).start()
    signal.signal(signal.SIGTERM, lambda *_: _term.set())

    def _preempt_watch():
        """Spot preemption watcher: an injected zone-scoped notice
        (the decode_zone_storm plan) gives this replica its ~30s
        grace window. Migration arm: evacuate every live session to
        peers, then exit. Full-replay arm (--no-migrate): streams
        break and the process dies, like a kill without notice."""
        while not _term.is_set():
            outcome = None
            try:
                outcome = faults.point('serve.preempt_notice',
                                       zone=args.zone)
            except faults.InjectedFault:
                outcome = faults.DROP
            if outcome is not faults.DROP:
                if _term.wait(0.25):
                    return
                continue
            if args.no_migrate:
                print(f'stub: preemption notice (zone={args.zone}); '
                      f'no-migrate arm — dying.', flush=True)
                state.aborted.set()
                time.sleep(0.5)
                os._exit(1)
            print(f'stub: preemption notice (zone={args.zone}); '
                  f'evacuating sessions to peers.', flush=True)
            state.begin_evacuation('preempt')
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with state.lock:
                    if state.inflight == 0:
                        break
                time.sleep(0.05)
            os._exit(0)

    if faults.active():
        threading.Thread(target=_preempt_watch,
                         daemon=True).start()
    print(f'stub replica listening on '
          f':{server.server_address[1]} seed={args.seed}', flush=True)
    server.serve_forever()


if __name__ == '__main__':
    main()
