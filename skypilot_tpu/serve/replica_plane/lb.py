"""Streaming HTTP load balancer for the replica plane.

Routes `/generate*` and `/v1/*` POSTs across the fleet:

  - prefix-cache / session affinity: the request body's chain-key
    hash (inference/affinity.py — the PrefixCache page hash of the
    prompt's first full KV page) is passed to the policy as the
    routing key; under PrefixAffinityPolicy, requests sharing a
    system prompt land on the replica already holding those pages,
    falling back to least-backlog when the target is saturated or
    not ready;
  - retry-on-death: a replica that refuses the connection, drops it
    before responding, or answers 503 (draining / engine dead) gets
    the request retried on another replica — but ONLY while nothing
    has been streamed to the client (once response headers are out,
    a retry would corrupt the stream; the client sees truncation
    instead, bounded to the dead replica's in-flight requests);
  - streaming pass-through: SSE responses are forwarded chunk by
    chunk as they arrive (TTFT through the LB is TTFT of the
    replica, not of the full generation).

Deliberately synchronous (ThreadingHTTPServer + requests), matching
the replica's own server: one OS thread per in-flight proxied
request is the honest cost model at local-fleet scale, and it keeps
the hot path out of the async-blocking lint's reach by construction.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from skypilot_tpu.inference import affinity
from skypilot_tpu.inference import sse
from skypilot_tpu.observability import REGISTRY
from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.observability import tracing
from skypilot_tpu.utils import ux_utils

#: Hop-by-hop headers never forwarded in either direction.
_HOP_HEADERS = frozenset((
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding',
    'upgrade', 'host', 'content-length'))

#: Upstream statuses that mean "this replica cannot take the request
#: right now" (draining, engine dead) rather than "the request is
#: bad" — safe to retry elsewhere before anything was streamed.
_RETRYABLE_STATUS = frozenset((502, 503))


class LBMetrics:
    """The LB's instrument bundle (one per policy label)."""

    def __init__(self, policy_name: str) -> None:
        self.routed = obs_catalog.counter(
            'skypilot_lb_requests_routed_total').labels(
                policy=policy_name)
        self.retried = obs_catalog.counter(
            'skypilot_lb_requests_retried_total').labels(
                policy=policy_name)
        self.affinity_requests = obs_catalog.counter(
            'skypilot_lb_affinity_requests_total')
        self.affinity_hits = obs_catalog.counter(
            'skypilot_lb_affinity_hits_total')
        # User-perceived latency, anchored at the FIRST attempt: a
        # retry after a replica death keeps the original clock, so
        # these reflect what the client waited, not the last hop.
        self.ttft_seconds = obs_catalog.histogram(
            'skypilot_lb_ttft_seconds')
        self.request_seconds = obs_catalog.histogram(
            'skypilot_lb_request_seconds')
        # Window counters for /fleet/status (Prometheus children keep
        # lifetime process totals across LB instances; these are THIS
        # LB's, so the bench's affinity ratio is per-run).
        self._lock = threading.Lock()
        self.n_routed = 0
        self.n_retried = 0
        self.n_affinity = 0
        self.n_affinity_hits = 0
        self.routed_per_replica: Dict[str, int] = {}

    def record_routed(self, replica: str) -> None:
        self.routed.inc()
        with self._lock:
            self.n_routed += 1
            self.routed_per_replica[replica] = \
                self.routed_per_replica.get(replica, 0) + 1

    def record_retried(self) -> None:
        self.retried.inc()
        with self._lock:
            self.n_retried += 1

    def record_affinity(self, hit: bool) -> None:
        self.affinity_requests.inc()
        with self._lock:
            self.n_affinity += 1
            if hit:
                self.n_affinity_hits += 1
        if hit:
            self.affinity_hits.inc()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'routed': self.n_routed,
                'retried': self.n_retried,
                'affinity_requests': self.n_affinity,
                'affinity_hits': self.n_affinity_hits,
                'affinity_hit_ratio': round(
                    self.n_affinity_hits / max(self.n_affinity, 1), 4),
                'routed_per_replica': dict(self.routed_per_replica),
            }


class PrefillPool:
    """The LB's view of the prefill pool (disaggregated serving):
    a round-robin rotation over the prefill-role ready set. Long
    prompts route here; everything else rides the decode-pool policy
    — including long prompts when this pool is empty or exhausted
    (the LB never fails a request because disaggregation is down)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._replicas: list = []
        self._i = 0

    def set_ready_replicas(self, replicas) -> None:
        with self._lock:
            self._replicas = list(replicas)

    @property
    def ready_replicas(self) -> list:
        with self._lock:
            return list(self._replicas)

    def select(self, exclude=None) -> Optional[str]:
        with self._lock:
            cands = [r for r in self._replicas
                     if not exclude or r not in exclude]
            if not cands:
                return None
            self._i += 1
            return cands[self._i % len(cands)]


def merge_migration_stats(views) -> Dict[str, Any]:
    """Fleet-level live-migration rollup for /fleet/status: sum the
    numeric counters (and the per-reason `migrations` dict) scraped
    from every replica's /stats `migration` block. Key lists
    (`migrated_in_keys`) are routing state, not dashboard material —
    skipped."""
    total: Dict[str, Any] = {}
    for view in views:
        part = getattr(view, 'migration', None) or {}
        for key, value in part.items():
            if isinstance(value, dict):
                sub = total.setdefault(key, {})
                for reason, count in value.items():
                    try:
                        sub[reason] = sub.get(reason, 0) + int(count)
                    except (TypeError, ValueError):
                        continue
            elif isinstance(value, (int, float)):
                total[key] = total.get(key, 0) + int(value)
    return total


def estimate_prompt_tokens(path: str, body: Dict[str, Any]) -> int:
    """Request prompt length in tokens, as well as the LB can know
    it: exact for token endpoints, chars/4 for text (the routing
    threshold only needs long-vs-short, not a tokenizer)."""
    try:
        if path in ('/generate', '/v1/generate'):
            rows = body.get('tokens') or []
            if rows and not isinstance(rows[0], list):
                rows = [rows]
            return max((len(r) for r in rows), default=0)
        if path in ('/generate_text', '/v1/generate_text'):
            prompts = body.get('prompts', '')
            if isinstance(prompts, list):
                prompts = max((str(p) for p in prompts), key=len,
                              default='')
            return len(str(prompts)) // 4
        if path == '/v1/completions':
            prompt = body.get('prompt', '')
            if isinstance(prompt, list):
                prompt = max((str(p) for p in prompt), key=len,
                             default='')
            return len(str(prompt)) // 4
        if path == '/v1/chat/completions':
            return sum(len(str(m.get('content', '')))
                       for m in (body.get('messages') or [])) // 4
    except (TypeError, ValueError, AttributeError):
        return 0
    return 0


def make_lb_server(policy, port: int, *, policy_name: str,
                   manager=None, page_size: int = 16,
                   max_retries: int = 2,
                   upstream_timeout_s: float = 660.0,
                   connect_timeout_s: float = 3.0,
                   disagg_threshold: int = 0,
                   prefill_pool: Optional[PrefillPool] = None,
                   trace_sample: float = 0.0,
                   trace_seed: Optional[int] = None,
                   slo_targets: Optional[Dict[str, float]] = None
                   ) -> ThreadingHTTPServer:
    """Build (not yet serving) the LB. `policy` is a
    LoadBalancingPolicy whose ready set the fleet controller keeps
    current; `manager` (optional) feeds the /fleet/status surface.
    The server exposes `.lb_metrics` for the bench harness.

    Disaggregated routing: with `disagg_threshold` > 0 and a
    `prefill_pool`, generation requests whose estimated prompt
    length is >= the threshold route to the prefill pool (whose
    replicas prefill and hand the KV chain to a decode replica);
    shorter requests keep prefix-affinity routing over the decode
    pool — the pool that actually holds the pages."""
    import requests as requests_lib

    metrics = LBMetrics(policy_name)
    if trace_sample > 0:
        # The LB is the trace head: it makes the sampling decision
        # for headerless requests. Replicas inherit the decision via
        # the propagated header, whatever their own sample rate.
        tracing.configure(sample=trace_sample, seed=trace_seed)
    slo_tracker = None
    if slo_targets:
        from skypilot_tpu.observability import slo as slo_mod
        slo_tracker = slo_mod.SloTracker(slo_targets)

    class Handler(BaseHTTPRequestHandler):
        # Runs on ThreadingHTTPServer worker threads: SKY008 assigns
        # every do_* method the 'http' role automatically (no
        # annotation needed). LB shared state (LBMetrics, PrefillPool,
        # the policy's ready set) is lock-disciplined — SKY003's
        # domain — rather than ownership-declared: many http threads
        # legitimately write it.

        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # -- GET: plane surfaces + pass-through -------------------------
        def do_GET(self):  # noqa: N802
            if self.path == '/readyz':
                ready = bool(policy.ready_replicas)
                self._json({'ready': ready,
                            'reasons': [] if ready
                            else ['no ready replicas']},
                           200 if ready else 503)
                return
            if self.path.startswith('/debug/trace/'):
                trace_id = self.path.rsplit('/', 1)[-1]
                trace = tracing.get_trace(trace_id)
                if trace is None:
                    self._json({'error': f'unknown trace {trace_id}'},
                               404)
                else:
                    self._json(trace)
                return
            if self.path == '/fleet/status':
                views = ([v.to_dict() for v in manager.views()]
                         if manager is not None else [])
                body = {'replicas': views,
                        'policy': policy_name,
                        'lb': metrics.snapshot()}
                if manager is not None:
                    migration = merge_migration_stats(
                        manager.views())
                    if migration:
                        body['migration'] = migration
                if slo_tracker is not None:
                    body['slo'] = slo_tracker.snapshot()
                if disagg_threshold > 0:
                    body['disagg'] = {
                        'prompt_threshold': disagg_threshold,
                        'prefill_pool':
                            (prefill_pool.ready_replicas
                             if prefill_pool is not None else []),
                    }
                self._json(body)
                return
            if self.path == '/metrics':
                body = REGISTRY.render().encode()
                self.send_response(200)
                self.send_header('Content-Type',
                                 REGISTRY.CONTENT_TYPE)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            # Anything else (/, /stats, /v1/models): pass through to
            # one ready replica — fleet replicas are homogeneous.
            self._proxy(body_bytes=None, key=None)

        # -- POST: routed generation requests ---------------------------
        def do_POST(self):  # noqa: N802
            length = int(self.headers.get('Content-Length', 0))
            body_bytes = self.rfile.read(length) if length else b''
            key = None
            try:
                parsed = json.loads(body_bytes) if body_bytes else {}
            except ValueError:
                parsed = None  # replica's 400 to give; route keyless
            long_prompt = False
            if isinstance(parsed, dict):
                key = affinity.request_affinity_key(
                    self.path, parsed, page_size=page_size)
                if disagg_threshold > 0 and prefill_pool is not None:
                    long_prompt = estimate_prompt_tokens(
                        self.path, parsed) >= disagg_threshold
            self._proxy(body_bytes=body_bytes, key=key,
                        long_prompt=long_prompt)

        def _proxy(self, body_bytes: Optional[bytes],
                   key: Optional[str],
                   long_prompt: bool = False) -> None:
            # First-attempt anchor: every retry after a replica death
            # keeps this clock, so LB-side TTFT/latency is what the
            # CLIENT waited, not the last attempt's slice of it.
            t0 = time.monotonic()
            ctx = tracing.parse_header(
                self.headers.get(tracing.HEADER))
            if ctx is None:
                ctx = tracing.new_ctx()
            root = tracing.start_span('lb.request', ctx,
                                      process='lb', path=self.path)
            status: Optional[int] = None
            ttft_s: Optional[float] = None
            try:
                tried = set()
                for attempt in range(max_retries + 1):
                    from_prefill = False
                    replica = None
                    with tracing.span('lb.route', root.ctx,
                                      process='lb') as route_span:
                        if long_prompt and prefill_pool is not None:
                            # Long prompts go to the prefill pool
                            # (their replicas hand the KV chain to
                            # the decode pool); an empty/exhausted
                            # pool falls back to normal decode
                            # routing — disaggregation being down
                            # degrades, it never 5xxes.
                            replica = prefill_pool.select(
                                exclude=tried)
                            from_prefill = replica is not None
                        if replica is None:
                            replica = policy.select_replica(
                                key=key, exclude=tried)
                        route_span.add(attempt=attempt,
                                       replica=replica or '',
                                       prefill=from_prefill)
                    if replica is None:
                        status = 503
                        self._json({'error': 'no ready replicas'},
                                   503)
                        return
                    if attempt == 0 and key is not None and \
                            not from_prefill and \
                            hasattr(policy, 'affinity_target'):
                        target = policy.affinity_target(key)
                        metrics.record_affinity(hit=replica == target)
                    metrics.record_routed(replica)
                    try:
                        done, status, ttft_s = self._forward(
                            replica, body_bytes, t0, root)
                    finally:
                        if not from_prefill:
                            policy.request_done(replica)
                    if done:
                        root.add(replica=replica,
                                 attempts=attempt + 1)
                        return
                    # Not-yet-streamed failure: retry elsewhere.
                    tried.add(replica)
                    metrics.record_retried()
                    ux_utils.log(f'LB: replica {replica} failed '
                                 f'before streaming; retrying '
                                 f'({attempt + 1}/{max_retries}).')
                status = 502
                self._json({'error': 'all replicas failed'}, 502)
            finally:
                root.end(status=status if status is not None else -1)
                if body_bytes is not None:
                    # Routed generation POSTs only — GET pass-through
                    # would pollute the latency distributions.
                    metrics.request_seconds.observe(
                        time.monotonic() - t0)
                    if ttft_s is not None:
                        metrics.ttft_seconds.observe(ttft_s)
                    if slo_tracker is not None:
                        slo_tracker.record_request(
                            error=(status is None or status >= 500),
                            shed=(status == 429),
                            ttft_ms=(ttft_s * 1000.0
                                     if ttft_s is not None else None))

        def _forward(self, replica: str,
                     body_bytes: Optional[bytes], t0: float, root
                     ) -> tuple:
            """Proxy one attempt. Returns (done, status, ttft_s):
            done = the client got an answer (including a truncated
            stream — headers are out) so no retry; status is the
            upstream code when one arrived; ttft_s is first response
            byte relative to `t0` (the FIRST attempt's start)."""
            url = f'http://{replica}{self.path}'
            headers = {k: v for k, v in self.headers.items()
                       if k.lower() not in _HOP_HEADERS}
            if root.ctx is not None:
                headers[tracing.HEADER] = tracing.format_header(
                    root.ctx)
            try:
                if body_bytes is None:
                    upstream = requests_lib.get(
                        url, headers=headers,
                        timeout=(connect_timeout_s,
                                 upstream_timeout_s), stream=True)
                else:
                    upstream = requests_lib.post(
                        url, data=body_bytes, headers=headers,
                        timeout=(connect_timeout_s,
                                 upstream_timeout_s), stream=True)
            except requests_lib.RequestException as e:
                ux_utils.log(f'LB: upstream {replica} unreachable '
                             f'({type(e).__name__}: {e}).')
                return False, None, None
            with upstream:
                if upstream.status_code in _RETRYABLE_STATUS:
                    return False, upstream.status_code, None
                is_stream = 'text/event-stream' in \
                    upstream.headers.get('Content-Type', '')
                if not is_stream:
                    try:
                        content = upstream.content
                    except requests_lib.RequestException as e:
                        ux_utils.log(f'LB: upstream {replica} died '
                                     f'mid-response ({e}).')
                        return False, None, None
                    ttft_s = time.monotonic() - t0
                    self.send_response(upstream.status_code)
                    for k, v in upstream.headers.items():
                        if k.lower() not in _HOP_HEADERS:
                            self.send_header(k, v)
                    self.send_header('Content-Length',
                                     str(len(content)))
                    self.end_headers()
                    self.wfile.write(content)
                    return True, upstream.status_code, ttft_s
                # SSE: headers out first, then bytes as they ARRIVE
                # (sse.pipe — iter_content would buffer whole short
                # streams to EOF and flatten TTFT/ITL through the LB).
                self.send_response(upstream.status_code)
                for k, v in upstream.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        self.send_header(k, v)
                self.end_headers()
                eof, first_at = sse.pipe(upstream, self.wfile)
                if not eof:
                    # Mid-stream replica death: the stream truncates
                    # (bounded blast radius — exactly the in-flight
                    # requests of the dead replica); never re-spliced.
                    ux_utils.log(f'LB: stream from {replica} '
                                 f'truncated.')
                ttft_s = (first_at - t0
                          if first_at is not None else None)
                return True, upstream.status_code, ttft_s

    server = ThreadingHTTPServer(('0.0.0.0', port), Handler)
    server.lb_metrics = metrics  # type: ignore[attr-defined]
    server.slo_tracker = slo_tracker  # type: ignore[attr-defined]
    return server
