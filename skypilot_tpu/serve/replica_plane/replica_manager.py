"""Replica manager: real local serve_lm processes + engine scraping.

Each replica is one `serve_lm` HTTP server process on its own port
(spawned by an injectable factory, so tests substitute stub replicas
or in-process handles). A scrape pass reads every live replica's
`/readyz` and JSON `/stats` into its `ReplicaView` — queue depth,
prefill backlog tokens, shed counter, prefix-cache hits — which the
fleet controller feeds to the EngineMetricsAutoscaler and the LB
policy's load map.

Termination ALWAYS goes through the drain contract (`drain()`):
  1. the view is marked DRAINING (the caller removes it from the
     routing set before calling — see FleetController.drain_replica);
  2. SIGTERM — the replica's own drain (inference/http_server.py)
     flips its /readyz to 503 and finishes in-flight requests;
  3. the manager waits for the process to exit on its own (bounded
     by `drain_grace_s`); only on timeout does it SIGKILL.
Never kill-then-reroute: a killed replica resets every in-flight
stream; a drained one finishes them.
"""
from __future__ import annotations

import dataclasses
import inspect
import itertools
import os
import signal as signal_lib
import socket
import subprocess
import sys
import threading
import time
import uuid as uuid_lib
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.serve.replica_plane.journal import (FleetJournal,
                                                      ReplicaRecord,
                                                      max_journaled_id)
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.utils import ux_utils

#: States a replica can occupy in the local plane (subset of the
#: serve-state enum: there is no PROVISIONING — process spawn is
#: instant — and no PREEMPTED).
_LIVE_STATES = (ReplicaStatus.STARTING, ReplicaStatus.READY,
                ReplicaStatus.NOT_READY)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


#: Env var carrying a replica's instance UUID into its process; the
#: replica echoes it in `GET /stats` (`instance_uuid`), which is how
#: adoption proves a pid/port still belongs to the journaled replica
#: rather than to whatever reused them after a crash.
INSTANCE_UUID_ENV = 'STPU_REPLICA_INSTANCE_UUID'


def pid_alive(pid: Optional[int]) -> bool:
    """Is `pid` a live (non-zombie) process? Zombies matter: an
    adopted replica that exited before we could wait() on it must
    read as dead, or the drain path would wait a full grace window
    on a corpse."""
    if pid is None or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # alive, owned by someone else
    try:
        with open(f'/proc/{pid}/stat', 'r', encoding='utf-8') as f:
            # Field 3 (after the parenthesized comm) is the state.
            return f.read().rsplit(')', 1)[-1].split()[0] != 'Z'
    except (OSError, IndexError):
        return True  # no /proc (non-Linux): kill(0) said alive


class AdoptedProcess:
    """Popen-shaped handle over a process we did NOT spawn (a
    verified adoption candidate from the journal). `poll()` can only
    report liveness, never the real exit code — the original parent
    (the dead controller) owned wait(); we report 0 once the pid is
    gone, which is correct for every decision this plane makes
    (drain completion, crash detection runs through /stats)."""

    def __init__(self, pid: int,
                 probe: Callable[[Optional[int]], bool] = pid_alive,
                 signal_fn: Callable[[int, int], None] = os.kill
                 ) -> None:
        self.pid = pid
        self._probe = probe
        self._signal = signal_fn

    def poll(self) -> Optional[int]:
        return None if self._probe(self.pid) else 0

    def send_signal(self, sig: int) -> None:
        self._signal(self.pid, sig)

    def terminate(self) -> None:
        self.send_signal(signal_lib.SIGTERM)

    def kill(self) -> None:
        self.send_signal(signal_lib.SIGKILL)

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f'pid {self.pid} did not exit')
            time.sleep(0.05)
        return 0


@dataclasses.dataclass
class ReplicaView:
    """One replica's last-scraped state, shared between the manager,
    the autoscaler feed, and the LB status surface."""
    replica_id: int
    port: int
    endpoint: str                      # '127.0.0.1:<port>'
    state: ReplicaStatus
    spawned_at: float
    proc: Any = None                   # Popen-shaped handle
    instance_uuid: str = ''            # journaled; echoed by /stats
    adopted: bool = False              # reattached after a restart
    ready: bool = False
    engine_healthy: bool = True
    scrape_failures: int = 0           # consecutive
    # Disaggregated pool membership: '' (unified), 'prefill', or
    # 'decode' — assigned at spawn, confirmed by the /stats echo.
    role: str = ''
    # Spot placement: the zone this replica models ('' = on-demand /
    # zoneless) and its hourly price — what /fleet/status needs for
    # the $/hour rollup and what the zone-scoped preemption storm
    # selects its victims by.
    zone: str = ''
    price_per_hour: float = 0.0
    queue_depth: int = 0
    prefill_backlog_tokens: int = 0
    requests_shed_total: int = 0
    prefix_hits: int = 0
    prefix_misses: int = 0
    # Tiered-cache state scraped from /stats `kv_spill` (zero when
    # the replica runs without a spill tier) — the fleet dashboard's
    # cache-residency signal next to the prefix hit rate.
    kv_spill_bytes: int = 0
    kv_spilled_pages: int = 0
    kv_restored_pages: int = 0
    # Multi-LoRA inventory scraped from /stats `adapters` (empty for
    # base-only replicas): which adapters this replica has device-
    # resident right now, and how many artifacts it can serve.
    adapters_loaded: List[str] = dataclasses.field(default_factory=list)
    adapters_inventory: int = 0
    # Live-migration counters scraped from /stats `migration` (empty
    # until the replica migrates or receives a chain) — the fleet
    # rollup in /fleet/status sums these across views.
    migration: Dict[str, Any] = dataclasses.field(default_factory=dict)
    last_stats: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def prefix_hit_rate(self) -> float:
        return self.prefix_hits / max(self.prefix_hits +
                                      self.prefix_misses, 1)

    def to_dict(self) -> Dict[str, Any]:
        return {
            'replica_id': self.replica_id,
            'endpoint': self.endpoint,
            'state': self.state.value,
            'adopted': self.adopted,
            'ready': self.ready,
            'engine_healthy': self.engine_healthy,
            'role': self.role,
            'zone': self.zone,
            'price_per_hour': self.price_per_hour,
            'queue_depth': self.queue_depth,
            'prefill_backlog_tokens': self.prefill_backlog_tokens,
            'requests_shed_total': self.requests_shed_total,
            'prefix_hits': self.prefix_hits,
            'prefix_misses': self.prefix_misses,
            'prefix_hit_rate': round(self.prefix_hit_rate, 4),
            'kv_spill_bytes': self.kv_spill_bytes,
            'kv_spilled_pages': self.kv_spilled_pages,
            'kv_restored_pages': self.kv_restored_pages,
            'adapters_loaded': list(self.adapters_loaded),
            'adapters_inventory': self.adapters_inventory,
        }


def serve_lm_factory(base_cmd: List[str],
                     env: Optional[Dict[str, str]] = None,
                     quiet: bool = True
                     ) -> Callable[[int, int], 'subprocess.Popen']:
    """Factory spawning `serve_lm` subprocesses: `base_cmd` is the
    full command line WITHOUT `--port` (appended per replica).
    `python -m skypilot_tpu.recipes.serve_lm --model ... --cpu` is
    the usual shape (recipes/serve_fleet.py builds it)."""

    def spawn(replica_id: int, port: int,
              instance_uuid: str = '',
              role: str = '',
              zone: str = '') -> 'subprocess.Popen':
        del replica_id
        out = subprocess.DEVNULL if quiet else None
        child_env = dict(env if env is not None else os.environ)
        if instance_uuid:
            child_env[INSTANCE_UUID_ENV] = instance_uuid
        cmd = base_cmd + ['--port', str(port)]
        if role:
            cmd += ['--role', role]
        if zone:
            cmd += ['--zone', zone]
        return subprocess.Popen(
            cmd, env=child_env,
            stdout=out, stderr=subprocess.STDOUT if quiet else None)

    return spawn


def stub_factory(extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None
                 ) -> Callable[..., 'subprocess.Popen']:
    """Factory spawning model-free stub replicas (stub.py) — the
    deterministic fleet for bench smokes."""

    def spawn(replica_id: int, port: int,
              instance_uuid: str = '',
              role: str = '',
              zone: str = '') -> 'subprocess.Popen':
        cmd = [sys.executable, '-m',
               'skypilot_tpu.serve.replica_plane.stub',
               '--port', str(port), '--seed', str(replica_id)]
        if role:
            cmd += ['--role', role]
        if zone:
            cmd += ['--zone', zone]
        cmd += list(extra_args or [])
        child_env = dict(env if env is not None else os.environ)
        if instance_uuid:
            child_env[INSTANCE_UUID_ENV] = instance_uuid
        return subprocess.Popen(cmd, env=child_env)

    return spawn


def _default_http_get(url: str, timeout: float
                      ) -> Tuple[int, Dict[str, Any]]:
    import requests as requests_lib
    resp = requests_lib.get(url, timeout=timeout)
    try:
        body = resp.json()
    except ValueError:
        body = {}
    return resp.status_code, body


class ReplicaManager:
    """Owns the replica processes and their scraped views.

    Injectables (all defaulted for production):
      factory(replica_id, port) -> Popen-shaped handle
          (.poll/.send_signal/.terminate/.kill/.wait);
      http_get(url, timeout) -> (status_code, json_dict);
      clock  -> monotonic seconds (virtual in tests);
      on_event(name, view) -> lifecycle hook; tests assert ordering
          of ('spawned','ready','not_ready','draining','sigterm',
          'drained','killed','dead') events — in particular that
          'draining' precedes 'sigterm' for every voluntary
          termination.
    """

    def __init__(self, factory: Callable[..., Any], *,
                 startup_grace_s: float = 180.0,
                 drain_grace_s: float = 30.0,
                 scrape_timeout_s: float = 3.0,
                 max_scrape_failures: int = 3,
                 http_get: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_event: Optional[Callable] = None,
                 state_dir: Optional[str] = None,
                 pid_probe: Callable[[Optional[int]], bool] = pid_alive,
                 signal_pid: Callable[[int, int], None] = os.kill,
                 reattach: Optional[Callable] = None) -> None:
        self._factory = factory
        # Factories that accept `instance_uuid` (all in-repo ones)
        # get the per-replica UUID; bare (rid, port) test lambdas
        # keep working, their replicas just never verify on adopt.
        try:
            params = inspect.signature(factory).parameters
            var_kw = any(p.kind == p.VAR_KEYWORD
                         for p in params.values())
            self._factory_takes_uuid = ('instance_uuid' in params or
                                        var_kw)
            self._factory_takes_role = 'role' in params or var_kw
            self._factory_takes_zone = 'zone' in params or var_kw
        except (TypeError, ValueError):
            self._factory_takes_uuid = False
            self._factory_takes_role = False
            self._factory_takes_zone = False
        self.startup_grace_s = startup_grace_s
        self.drain_grace_s = drain_grace_s
        self.scrape_timeout_s = scrape_timeout_s
        self.max_scrape_failures = max_scrape_failures
        self._http_get = http_get or _default_http_get
        self._clock = clock
        self._on_event = on_event or (lambda name, view: None)
        self._pid_probe = pid_probe
        self._signal_pid = signal_pid
        self._reattach = reattach or (
            lambda rec: AdoptedProcess(rec.pid, probe=pid_probe,
                                       signal_fn=signal_pid))
        self._lock = threading.Lock()
        self._replicas: Dict[int, ReplicaView] = {}
        self._ids = itertools.count(1)
        self._journal: Optional[FleetJournal] = None
        if state_dir is not None:
            self._journal = FleetJournal(
                os.path.join(state_dir, 'fleet.journal'))
        self._gauge = obs_catalog.gauge('skypilot_replica_plane_replicas')
        self._scrape_errors = obs_catalog.counter(
            'skypilot_replica_plane_scrape_errors_total')
        self._adoptions = obs_catalog.counter(
            'skypilot_fleet_adoptions_total')
        self._orphans_reaped = obs_catalog.counter(
            'skypilot_fleet_orphans_reaped_total')

    # -- journal write-through -------------------------------------------
    # (FleetJournal serializes appends under its own lock; taking the
    # manager lock here too would hold it across an fsync.)
    def _journal_spawn(self, view: ReplicaView) -> None:
        if self._journal is None:
            return
        self._journal.append(  # stpu: ignore[SKY003]
            'spawn', **ReplicaRecord(
                replica_id=view.replica_id, port=view.port,
                endpoint=view.endpoint,
                instance_uuid=view.instance_uuid,
                state=view.state.value,
                pid=getattr(view.proc, 'pid', None),
                role=view.role, zone=view.zone,
                price_per_hour=view.price_per_hour).to_fields())

    def _journal_state(self, view: ReplicaView) -> None:
        if self._journal is None:
            return
        self._journal.append(  # stpu: ignore[SKY003]
            'state', replica_id=view.replica_id,
            state=view.state.value)

    def _journal_terminate(self, replica_id: int) -> None:
        if self._journal is None:
            return
        self._journal.append(  # stpu: ignore[SKY003]
            'terminate', replica_id=replica_id)

    # -- lifecycle -------------------------------------------------------
    def spawn(self, role: str = '', zone: str = '',
              price_per_hour: float = 0.0) -> ReplicaView:
        """Spawn a replica; `role` ('' | 'prefill' | 'decode')
        selects its disaggregated pool and is forwarded to factories
        that accept it (serve_lm/stub factories pass --role).
        `zone`/`price_per_hour` label a spot replica with its
        placement (journaled; `zone` is forwarded to factories that
        accept it, so the replica can answer zone-scoped preemption
        storms)."""
        with self._lock:
            rid = next(self._ids)
        port = free_port()
        instance_uuid = uuid_lib.uuid4().hex
        kwargs = {}
        if self._factory_takes_uuid:
            kwargs['instance_uuid'] = instance_uuid
        if role and self._factory_takes_role:
            kwargs['role'] = role
        if zone and self._factory_takes_zone:
            kwargs['zone'] = zone
        proc = self._factory(rid, port, **kwargs)
        view = ReplicaView(replica_id=rid, port=port,
                           endpoint=f'127.0.0.1:{port}',
                           state=ReplicaStatus.STARTING,
                           spawned_at=self._clock(), proc=proc,
                           instance_uuid=instance_uuid, role=role,
                           zone=zone, price_per_hour=price_per_hour)
        with self._lock:
            self._replicas[rid] = view
        self._journal_spawn(view)
        self._on_event('spawned', view)
        return view

    # -- adoption (controller restart) -----------------------------------
    def _verify_candidate(self, rec: ReplicaRecord) -> bool:
        """Is the journaled process still OUR replica? Two proofs,
        both required: the journaled pid is a live process, and the
        journaled port's `/stats` echoes the journaled instance
        UUID. The UUID check is what defeats pid/port reuse — a
        recycled pid or a stranger's server on the old port fails
        it, and we must never route to (or signal) a process we
        cannot prove is ours."""
        if not rec.instance_uuid or not self._pid_probe(rec.pid):
            return False
        try:
            code, stats = self._http_get(
                f'http://{rec.endpoint}/stats', self.scrape_timeout_s)
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'adopt: replica {rec.replica_id} at '
                         f'{rec.endpoint} not scrapeable ({e}).')
            return False
        return (code == 200 and
                stats.get('instance_uuid') == rec.instance_uuid)

    def adopt(self, block_drains: bool = False) -> Dict[str, Any]:
        """Crash recovery: replay the journal of the previous
        controller generation and reattach what survived it.

        Per journaled live record:
          - VERIFIED (pid alive + /stats echoes the instance UUID)
            and not mid-drain: reattach as a live STARTING view —
            the next scrape pass re-earns READY and the controller
            pushes it back into the LB ring (same endpoint string,
            so consistent-hash affinity keys land exactly where
            their KV pages still live);
          - VERIFIED but journaled DRAINING: the crash interrupted a
            scale-down — resume the drain (SIGTERM -> wait), never
            readmit to routing;
          - UNVERIFIABLE (dead pid, unreachable port, UUID mismatch
            from pid/port reuse): an orphan. If the journaled pid is
            still a live process we ask it to drain with SIGTERM —
            never SIGKILL: a reused pid belongs to someone else, and
            SIGTERM is the only signal an innocent process gets to
            decline — then drop the record.

        Returns {'adopted': [...], 'resumed_drains': [...],
        'orphans': [...]} (replica ids). `block_drains` makes the
        resumed drains synchronous (tests); by default they run in
        daemon threads so a restart is not gated on a full drain
        grace window."""
        if self._journal is None:
            return {'adopted': [], 'resumed_drains': [], 'orphans': []}
        records = self._journal.replay()
        highest = max_journaled_id(self._journal.path)
        if highest:
            with self._lock:
                self._ids = itertools.count(highest + 1)
        adopted: List[int] = []
        resumed: List[int] = []
        orphans: List[int] = []
        for rid in sorted(records):
            rec = records[rid]
            if self._verify_candidate(rec):
                view = ReplicaView(
                    replica_id=rid, port=rec.port,
                    endpoint=rec.endpoint,
                    state=(ReplicaStatus.DRAINING
                           if rec.state == ReplicaStatus.DRAINING.value
                           else ReplicaStatus.STARTING),
                    spawned_at=self._clock(),
                    proc=self._reattach(rec),
                    instance_uuid=rec.instance_uuid, adopted=True,
                    role=rec.role, zone=rec.zone,
                    price_per_hour=rec.price_per_hour)
                with self._lock:
                    self._replicas[rid] = view
                if view.state == ReplicaStatus.DRAINING:
                    ux_utils.log(f'adopt: replica {rid} was '
                                 f'mid-drain; resuming the drain.')
                    self._journal_state(view)
                    self._on_event('adopt_resume_drain', view)
                    resumed.append(rid)
                    if block_drains:
                        self.drain(rid)
                    else:
                        threading.Thread(target=self.drain,
                                         args=(rid,),
                                         daemon=True).start()
                else:
                    ux_utils.log(
                        f'adopt: replica {rid} verified alive at '
                        f'{rec.endpoint} (pid {rec.pid}); '
                        f'reattached.')
                    self._adoptions.inc()
                    self._journal_spawn(view)
                    self._on_event('adopted', view)
                    adopted.append(rid)
                continue
            # Orphan: stale or unverifiable. Politely ask a
            # still-live pid to drain; never SIGKILL (the pid may
            # have been reused by an innocent process that is free
            # to ignore SIGTERM — SIGKILL would not be).
            if self._pid_probe(rec.pid):
                ux_utils.error(
                    f'adopt: replica {rid} (pid {rec.pid}, '
                    f'{rec.endpoint}) is unverifiable; sending '
                    f'SIGTERM and dropping it.')
                try:
                    self._signal_pid(rec.pid, signal_lib.SIGTERM)
                except OSError as e:
                    ux_utils.log(f'adopt: SIGTERM to orphan pid '
                                 f'{rec.pid} failed ({e}).')
            else:
                ux_utils.log(f'adopt: replica {rid} (pid {rec.pid}) '
                             f'is gone; dropping its record.')
            self._orphans_reaped.inc()
            self._journal_terminate(rid)
            orphans.append(rid)
        self._update_gauges()
        return {'adopted': adopted, 'resumed_drains': resumed,
                'orphans': orphans}

    def views(self) -> List[ReplicaView]:
        with self._lock:
            return list(self._replicas.values())

    def view(self, replica_id: int) -> Optional[ReplicaView]:
        with self._lock:
            return self._replicas.get(replica_id)

    def ready_endpoints(self,
                        role: Optional[str] = None) -> List[str]:
        """READY endpoints, optionally filtered by pool. `role=None`
        returns every ready replica (the unified-fleet behavior);
        'decode' additionally matches role-less replicas so a mixed
        fleet keeps its unified members serving decode traffic."""
        with self._lock:
            views = [v for v in self._replicas.values()
                     if v.state == ReplicaStatus.READY and v.ready]
        if role is None:
            return [v.endpoint for v in views]
        if role == 'decode':
            return [v.endpoint for v in views
                    if v.role in ('decode', '')]
        return [v.endpoint for v in views if v.role == role]

    def mark_draining(self, replica_id: int) -> None:
        """Step 1 of the drain contract: the replica leaves the
        routing set (the caller pushes the shrunken ready set to the
        LB policy before SIGTERM is sent)."""
        view = self.view(replica_id)
        if view is None or view.state not in _LIVE_STATES:
            return
        view.state = ReplicaStatus.DRAINING
        view.ready = False
        self._journal_state(view)
        self._on_event('draining', view)

    def drain(self, replica_id: int) -> None:
        """Steps 2-3: SIGTERM, then wait for the replica's own drain
        to finish (process exits 0 by itself); SIGKILL only past the
        grace window. Blocking — callers wanting async run it in a
        thread (FleetController does)."""
        view = self.view(replica_id)
        if view is None or view.proc is None:
            return
        if view.state != ReplicaStatus.DRAINING:
            self.mark_draining(replica_id)
        try:
            view.proc.send_signal(signal_lib.SIGTERM)
        except (OSError, ValueError) as e:
            ux_utils.log(f'replica {replica_id}: SIGTERM failed '
                         f'({e}); process likely already gone.')
        self._on_event('sigterm', view)
        deadline = self._clock() + self.drain_grace_s
        while self._clock() < deadline:
            if view.proc.poll() is not None:
                view.state = ReplicaStatus.SHUTDOWN
                self._journal_state(view)
                self._on_event('drained', view)
                return
            time.sleep(0.05)
        ux_utils.error(f'replica {replica_id}: drain grace '
                       f'({self.drain_grace_s}s) expired; killing.')
        try:
            view.proc.kill()
        except OSError as e:
            ux_utils.log(f'replica {replica_id}: kill failed ({e}).')
        view.state = ReplicaStatus.SHUTDOWN
        self._journal_state(view)
        self._on_event('killed', view)

    def fail(self, replica_id: int) -> None:
        """Involuntary teardown of a replica already observed dead
        (process exited, engine scheduler died): make sure the
        process is gone and mark FAILED so the controller replaces
        it. This is the ONE path that skips the drain — there is
        nothing left to drain."""
        view = self.view(replica_id)
        if view is None:
            return
        if view.proc is not None and view.proc.poll() is None:
            try:
                view.proc.kill()
            except OSError as e:
                ux_utils.log(f'replica {replica_id}: kill failed '
                             f'({e}).')
        view.state = ReplicaStatus.FAILED
        view.ready = False
        self._journal_state(view)
        self._on_event('dead', view)

    def remove(self, replica_id: int) -> None:
        """Forget a terminal replica's view (keeps `views()` bounded
        in long-running fleets)."""
        with self._lock:
            view = self._replicas.get(replica_id)
            if view is not None and view.state.is_terminal():
                del self._replicas[replica_id]
            else:
                return
        self._journal_terminate(replica_id)

    def shutdown(self) -> None:
        """Drain every live replica, in parallel."""
        live = [v for v in self.views() if v.state in _LIVE_STATES or
                v.state == ReplicaStatus.DRAINING]
        for view in live:
            self.mark_draining(view.replica_id)
        threads = [threading.Thread(target=self.drain,
                                    args=(v.replica_id,), daemon=True)
                   for v in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.drain_grace_s + 5.0)

    # -- scraping --------------------------------------------------------
    def scrape_once(self) -> None:
        """One pass over live replicas: process liveness, /readyz,
        /stats. HTTP happens outside the manager lock (a hung replica
        must not block spawns)."""
        for view in self.views():
            if view.state not in _LIVE_STATES:
                continue
            if view.proc is not None and view.proc.poll() is not None:
                # Exited without being asked: crashed or killed.
                ux_utils.error(
                    f'replica {view.replica_id} process exited '
                    f'(rc={view.proc.poll()}); marking FAILED.')
                view.state = ReplicaStatus.FAILED
                view.ready = False
                self._journal_state(view)
                self._on_event('dead', view)
                continue
            self._scrape_replica(view)
        self._update_gauges()

    def _scrape_replica(self, view: ReplicaView) -> None:
        base = f'http://{view.endpoint}'
        try:
            code, _body = self._http_get(f'{base}/readyz',
                                         self.scrape_timeout_s)
            ready = code == 200
            _code, stats = self._http_get(f'{base}/stats',
                                          self.scrape_timeout_s)
        except Exception as e:  # pylint: disable=broad-except
            view.scrape_failures += 1
            self._scrape_errors.inc()
            age = self._clock() - view.spawned_at
            if view.state == ReplicaStatus.STARTING:
                if age > self.startup_grace_s:
                    ux_utils.error(
                        f'replica {view.replica_id} not scrapeable '
                        f'within {self.startup_grace_s}s ({e}); '
                        f'failing it.')
                    self.fail(view.replica_id)
                return
            if view.scrape_failures >= self.max_scrape_failures:
                if view.ready or view.state == ReplicaStatus.READY:
                    ux_utils.log(
                        f'replica {view.replica_id}: '
                        f'{view.scrape_failures} consecutive scrape '
                        f'failures ({e}); marking NOT_READY.')
                transitioned = view.state != ReplicaStatus.NOT_READY
                view.ready = False
                view.state = ReplicaStatus.NOT_READY
                if transitioned:
                    self._journal_state(view)
                self._on_event('not_ready', view)
            return
        view.scrape_failures = 0
        view.ready = ready
        view.last_stats = stats
        view.queue_depth = int(stats.get('queued', 0) or 0)
        view.prefill_backlog_tokens = int(
            stats.get('prefill_backlog_tokens', 0) or 0)
        view.requests_shed_total = int(
            stats.get('requests_shed', 0) or 0)
        view.engine_healthy = bool(stats.get('healthy', True))
        # The replica's own role echo wins over the spawn-time label
        # (an adopted replica's journaled role may predate a config
        # change; the process knows what it is actually running).
        view.role = str(stats.get('role', view.role) or view.role)
        prefix = stats.get('prefix_cache') or {}
        view.prefix_hits = int(prefix.get('hits', 0) or 0)
        view.prefix_misses = int(prefix.get('misses', 0) or 0)
        spill = stats.get('kv_spill') or {}
        view.kv_spill_bytes = int(spill.get('bytes', 0) or 0)
        view.kv_spilled_pages = int(spill.get('spilled_pages', 0)
                                    or 0)
        view.kv_restored_pages = int(spill.get('restored_pages', 0)
                                     or 0)
        adapters = stats.get('adapters') or {}
        view.adapters_loaded = list(adapters.get('loaded') or [])
        view.adapters_inventory = len(adapters.get('inventory') or [])
        view.migration = dict(stats.get('migration') or {})
        if ready and view.state in (ReplicaStatus.STARTING,
                                    ReplicaStatus.NOT_READY):
            view.state = ReplicaStatus.READY
            self._journal_state(view)
            self._on_event('ready', view)
        elif not ready and view.state == ReplicaStatus.READY:
            view.state = ReplicaStatus.NOT_READY
            self._journal_state(view)
            self._on_event('not_ready', view)

    def _update_gauges(self) -> None:
        counts: Dict[str, int] = {}
        for view in self.views():
            counts[view.state.value] = counts.get(view.state.value,
                                                  0) + 1
        for status in ReplicaStatus:
            self._gauge.labels(state=status.value).set(
                counts.get(status.value, 0))
