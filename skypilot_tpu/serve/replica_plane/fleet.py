"""Fleet controller: scraped engine signals -> autoscaler -> replica
manager, with every scale-down going through the drain contract.

One `tick()`:
  1. scrape every replica (/readyz + /stats into ReplicaViews);
  2. cull replicas whose process or engine scheduler died (FAILED;
     the only non-drain teardown — nothing left to drain);
  3. push the ready set + load map into the LB policy (a draining or
     dead replica stops receiving traffic HERE, before any signal is
     sent to it);
  4. feed engine signals to the autoscaler and evaluate;
  5. SCALE_UP -> spawn; SCALE_DOWN -> drain-before-kill the
     least-loaded victims in a background thread.

Deterministic by injection: the manager's clock/http_get and the
autoscaler's clock are injectable, so unit tests drive ticks with a
virtual clock and stub scrapes — no sleeps.
"""
from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Set

from skypilot_tpu.observability import catalog as obs_catalog
from skypilot_tpu.robustness import faults
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.utils import common_utils
from skypilot_tpu.serve.replica_plane.replica_manager import (
    ReplicaManager, ReplicaView)
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.utils import ux_utils

#: Consecutive tick failures before the controller declares itself
#: degraded (error log + `skypilot_fleet_controller_degraded` gauge)
#: — the same 3-strike fuse the engine scheduler uses: one failure
#: is noise, three in a row is a condition.
_TICK_FAILURE_STRIKES = 3


def _default_http_post(url: str, body: dict,
                       timeout: float = 3.0) -> int:
    import requests as requests_lib
    return requests_lib.post(url, json=body,
                             timeout=timeout).status_code


class FleetController:

    # The control loop is single-threaded by design: run()/tick()/
    # safe_tick()/wait_ready() all execute on the one 'watcher'
    # thread (the serve_fleet entrypoint's main loop), so controller
    # state needs no locks — SKY008 verifies nothing else touches it.
    # Drain worker threads only call manager.drain; they never write
    # controller state.
    _STPU_OWNERS = {
        '_pushed_peers': 'watcher',
        '_peer_backoff': 'watcher',
        '_peer_retry_at': 'watcher',
        '_pinned_keys': 'watcher',
        '_rebalance_hot': 'watcher',
        '_rebalance_streak': 'watcher',
        '_drain_threads': 'watcher',
        'consecutive_tick_failures': 'watcher',
    }

    def __init__(self, manager: ReplicaManager,
                 policy, autoscaler: 'autoscalers.Autoscaler', *,
                 interval_s: float = 1.0,
                 clock: Optional[Callable[[], float]] = None,
                 drain_in_thread: bool = True,
                 prefill_autoscaler:
                 Optional['autoscalers.Autoscaler'] = None,
                 prefill_pool=None,
                 http_post: Optional[Callable] = None,
                 rebalance_skew: float = 0.0,
                 rebalance_ticks: int = 3,
                 rebalance_sessions: int = 2) -> None:
        self.manager = manager
        self.policy = policy
        self.autoscaler = autoscaler
        # Disaggregated mode (prefill_autoscaler set): the fleet is
        # TWO pools. `policy` routes the decode pool (prefix affinity
        # keys point at the replicas holding the pages);
        # `prefill_pool` (lb.PrefillPool) receives the prefill-role
        # ready set; the prefill autoscaler runs on prefill backlog
        # tokens while the decode autoscaler keeps its queue/shed
        # signals. The controller also pushes the live decode set to
        # every prefill replica (POST /kv/peers) so handoffs target
        # replicas that exist.
        self.prefill_autoscaler = prefill_autoscaler
        self.prefill_pool = prefill_pool
        self.disagg = prefill_autoscaler is not None
        self._http_post = http_post or _default_http_post
        self._pushed_peers: dict = {}   # endpoint -> peer set sent
        # Failed peer pushes retry on a per-endpoint decorrelated
        # backoff (seeded from the endpoint string, so schedules are
        # reproducible), not every tick.
        self._peer_backoff: Dict[str, common_utils.Backoff] = {}
        self._peer_retry_at: Dict[str, float] = {}
        # Migrated-in affinity keys already pinned per endpoint (the
        # replica's /stats list is a bounded ring; pinning only new
        # keys avoids churning the policy's pin LRU every tick).
        self._pinned_keys: Dict[str, Set[str]] = {}
        # Hot-spot rebalancing: when one replica's engine load stays
        # above `rebalance_skew` x the pool median for
        # `rebalance_ticks` consecutive ticks, ask it to migrate up
        # to `rebalance_sessions` of its deepest sessions to the
        # coldest replica. skew <= 0 disables.
        self.rebalance_skew = rebalance_skew
        self.rebalance_ticks = rebalance_ticks
        self.rebalance_sessions = rebalance_sessions
        self._rebalance_hot = ''
        self._rebalance_streak = 0
        self.interval_s = interval_s
        self._clock = clock if clock is not None else time.time
        # Tests flip this off to make drains synchronous (ordering
        # assertions without joins).
        self._drain_in_thread = drain_in_thread
        self._drain_threads: List[threading.Thread] = []
        self._shutdown = threading.Event()
        self.consecutive_tick_failures = 0
        self._tick_errors = obs_catalog.counter(
            'skypilot_fleet_tick_errors_total')
        self._degraded = obs_catalog.gauge(
            'skypilot_fleet_controller_degraded')
        self._degraded.set(0)

    # -- scaling actions -------------------------------------------------
    def _push_routing(self) -> None:
        """Ready set + load map into the policy. The load map is the
        affinity policy's saturation/fallback signal: engine-reported
        prefill backlog tokens plus queue depth (token-dominated on
        purpose — a 4k-token backlog is heavier than 4 queued short
        requests). Disaggregated fleets split the ready set: the
        routing policy sees the DECODE pool (affinity keys must point
        at the pool holding the pages), the LB's PrefillPool gets the
        prefill-role set, and every prefill replica learns the live
        decode set via POST /kv/peers."""
        ready = self.manager.ready_endpoints(
            'decode' if self.disagg else None)
        self.policy.set_ready_replicas(ready)
        if hasattr(self.policy, 'set_replica_load'):
            self.policy.set_replica_load({
                v.endpoint:
                    v.prefill_backlog_tokens + v.queue_depth
                for v in self.manager.views()
                if v.endpoint in ready})
        self._sync_session_pins()
        # Every serving replica learns the rest of its pool (minus
        # itself) so evacuations — drain, preemption, rebalance —
        # have affinity-chosen targets; prefill replicas additionally
        # learn the full decode set for KV handoffs.
        pushes = {endpoint: sorted(set(ready) - {endpoint})
                  for endpoint in ready}
        if self.disagg:
            prefill_ready = self.manager.ready_endpoints('prefill')
            if self.prefill_pool is not None:
                self.prefill_pool.set_ready_replicas(prefill_ready)
            want = sorted(ready)
            for endpoint in prefill_ready:
                pushes[endpoint] = want
        self._push_decode_peers(pushes)

    def _push_decode_peers(self,
                           pushes: Dict[str, List[str]]) -> None:
        """Tell each replica where its decode peers are (only when
        its view changed — the push is a no-op per-tick otherwise).
        A failed push is retried on that endpoint's decorrelated
        backoff schedule (a down replica must not eat one connect
        timeout per tick forever); the replica keeps its last set
        and falls back to local serving if every peer in it died."""
        now = self._clock()
        for endpoint, want in pushes.items():
            if self._pushed_peers.get(endpoint) == want:
                continue
            if not want and endpoint not in self._pushed_peers:
                continue  # nothing to tell a single-replica pool
            if now < self._peer_retry_at.get(endpoint, 0.0):
                continue  # backing off this endpoint
            try:
                code = self._http_post(
                    f'http://{endpoint}/kv/peers', {'decode': want})
            except Exception as e:  # pylint: disable=broad-except
                self._defer_peer_push(endpoint, now, f'failed ({e})')
                continue
            if code == 200:
                self._pushed_peers[endpoint] = want
                self._peer_backoff.pop(endpoint, None)
                self._peer_retry_at.pop(endpoint, None)
            else:
                self._defer_peer_push(endpoint, now,
                                      f'answered {code}')
        # Forget pushes to replicas that left the fleet.
        for endpoint in list(self._pushed_peers):
            if endpoint not in pushes:
                del self._pushed_peers[endpoint]
        for endpoint in list(self._peer_retry_at):
            if endpoint not in pushes:
                self._peer_retry_at.pop(endpoint, None)
                self._peer_backoff.pop(endpoint, None)

    def _defer_peer_push(self, endpoint: str, now: float,
                         why: str) -> None:
        """Schedule the next /kv/peers attempt for `endpoint` on its
        decorrelated backoff (seeded from the endpoint string so the
        schedule is reproducible across controller restarts)."""
        backoff = self._peer_backoff.get(endpoint)
        if backoff is None:
            backoff = common_utils.Backoff(
                initial=max(self.interval_s, 0.5), max_backoff=30.0,
                jitter=True,
                rng=random.Random(zlib.crc32(endpoint.encode())))
            self._peer_backoff[endpoint] = backoff
        delay = backoff.current_backoff()
        self._peer_retry_at[endpoint] = now + delay
        ux_utils.log(f'fleet: /kv/peers push to {endpoint} {why}; '
                     f'retrying in {delay:.1f}s.')

    def _sync_session_pins(self) -> None:
        """Scraped migrated-in affinity keys -> policy session pins,
        so follow-up requests for a migrated session land on the
        replica now holding its warm pages instead of the ring's
        stale owner. Only keys not yet pinned are pushed (the
        replica reports a bounded ring of recent keys)."""
        if not hasattr(self.policy, 'pin_key'):
            return
        live = set()
        for view in self.manager.views():
            live.add(view.endpoint)
            keys = (view.migration or {}).get('migrated_in_keys')
            if not keys:
                continue
            seen = self._pinned_keys.setdefault(view.endpoint, set())
            for key in keys:
                if key not in seen:
                    seen.add(key)
                    self.policy.pin_key(key, view.endpoint)
            if len(seen) > 4096:
                # The replica's ring evicted most of these long ago;
                # restart tracking from what it still reports.
                self._pinned_keys[view.endpoint] = set(keys)
        for endpoint in list(self._pinned_keys):
            if endpoint not in live:
                del self._pinned_keys[endpoint]

    def drain_replica(self, view: ReplicaView) -> None:  # stpu: entry[watcher]
        """THE drain contract, in order: mark not-ready -> stop
        routing -> evacuate KV chains to survivors -> SIGTERM ->
        wait for the replica's own drain. Never kill-then-reroute."""
        self.manager.mark_draining(view.replica_id)
        self._push_routing()  # routing stops BEFORE any signal
        for scaler in (self.autoscaler, self.prefill_autoscaler):
            if scaler is not None and hasattr(scaler, 'forget'):
                scaler.forget(view.endpoint)
        # Drain-by-migration: ask the victim to ship its active KV
        # chains to affinity-chosen survivors while routing is
        # already off. The POST returns once sessions are detached
        # (the ships ride the in-flight handler threads, which the
        # replica's own drain waits out); a failed POST is fine —
        # SIGTERM triggers the same evacuation replica-side.
        try:
            self._http_post(f'http://{view.endpoint}/kv/evacuate',
                            {'reason': 'drain'})
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'fleet: /kv/evacuate to draining replica '
                         f'{view.replica_id} failed ({e}); it will '
                         f'evacuate on SIGTERM.')
        if self._drain_in_thread:
            # Prune finished drains first: over a long-running fleet
            # the list would otherwise grow one dead Thread per
            # scale-down, forever.
            self._drain_threads = [t for t in self._drain_threads
                                   if t.is_alive()]
            thread = threading.Thread(
                target=self.manager.drain, args=(view.replica_id,),
                daemon=True)
            thread.start()
            self._drain_threads.append(thread)
        else:
            self.manager.drain(view.replica_id)

    def _maybe_rebalance(self) -> None:
        """Hot-spot rebalancing: sustained per-replica load skew
        (one replica's engine load above `rebalance_skew` x the pool
        median for `rebalance_ticks` consecutive ticks, same replica
        throughout) triggers a bounded evacuation — the hottest
        replica ships up to `rebalance_sessions` of its deepest
        sessions' chains to the coldest replica between requests.
        One detection, one POST: the streak resets after firing so a
        persistent imbalance re-arms rather than machine-gunning."""
        if self.rebalance_skew <= 0:
            return
        ready = set(self.manager.ready_endpoints(
            'decode' if self.disagg else None))
        loads = {v.endpoint: v.prefill_backlog_tokens + v.queue_depth
                 for v in self.manager.views()
                 if v.endpoint in ready}
        if len(loads) < 2:
            self._rebalance_streak = 0
            return
        ordered = sorted(loads.values())
        median = ordered[len(ordered) // 2]
        hottest = max(loads, key=lambda e: loads[e])
        coldest = min(loads, key=lambda e: loads[e])
        if loads[hottest] <= self.rebalance_skew * max(median, 1.0):
            self._rebalance_streak = 0
            return
        if hottest != self._rebalance_hot:
            self._rebalance_hot = hottest
            self._rebalance_streak = 0
        self._rebalance_streak += 1
        if self._rebalance_streak < self.rebalance_ticks:
            return
        self._rebalance_streak = 0
        ux_utils.log(f'fleet: rebalance — {hottest} load '
                     f'{loads[hottest]} > {self.rebalance_skew}x '
                     f'pool median {median}; migrating up to '
                     f'{self.rebalance_sessions} sessions to '
                     f'{coldest}.')
        try:
            self._http_post(
                f'http://{hottest}/kv/evacuate',
                {'reason': 'rebalance', 'target': coldest,
                 'max_sessions': self.rebalance_sessions})
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'fleet: rebalance /kv/evacuate to '
                         f'{hottest} failed ({e}); will re-detect.')

    def _pick_victims(self, candidates: List[ReplicaView],
                      count: int) -> List[ReplicaView]:
        """Least-valuable first: replicas still starting (nothing
        in-flight, no hot KV pages), then the lowest engine load,
        newest id as the tie-break."""
        ordered = sorted(
            candidates,
            key=lambda v: (v.state != ReplicaStatus.STARTING,
                           v.prefill_backlog_tokens + v.queue_depth,
                           -v.replica_id))
        return ordered[:max(0, count)]

    # -- control loop ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:  # stpu: entry[watcher]
        faults.point('fleet.tick')  # chaos: controller-loop failures
        now = now if now is not None else self._clock()
        self.manager.scrape_once()

        # Cull replicas whose engine scheduler died: /readyz says 503
        # forever, the process idles. Replace, don't drain — the
        # in-flight work is already lost (crash-only containment).
        for view in self.manager.views():
            if view.state in (ReplicaStatus.READY,
                              ReplicaStatus.NOT_READY) and \
                    not view.engine_healthy:
                ux_utils.error(f'replica {view.replica_id}: engine '
                               'dead; replacing.')
                self.manager.fail(view.replica_id)

        self._push_routing()
        self._maybe_rebalance()

        views = self.manager.views()
        if self.disagg:
            # Two pools, two autoscalers: the decode pool scales on
            # queue/shed pressure, the prefill pool on its own
            # signals (prefill backlog tokens). Victims are picked
            # within their pool — a decode scale-down can never
            # drain a prefill replica.
            self._scale_pool(
                [v for v in views if v.role in ('decode', '')],
                self.autoscaler, 'decode', now)
            self._scale_pool(
                [v for v in views if v.role == 'prefill'],
                self.prefill_autoscaler, 'prefill', now)
        else:
            self._scale_pool(views, self.autoscaler, '', now)

        # Forget terminal views so `views()` stays bounded.
        for view in views:
            if view.state.is_terminal():
                self.manager.remove(view.replica_id)

    def _scale_pool(self, views: List[ReplicaView], autoscaler,
                    role: str, now: float) -> None:
        """Feed one pool's scraped signals to its autoscaler and act
        on the decision (spawn carries the pool's role)."""
        ready = [v for v in views
                 if v.state == ReplicaStatus.READY and v.ready]
        launching = [v for v in views
                     if v.state == ReplicaStatus.STARTING]

        if isinstance(autoscaler,
                      autoscalers.EngineMetricsAutoscaler):
            for view in ready:
                autoscaler.observe(
                    view.endpoint,
                    queue_depth=view.queue_depth,
                    prefill_backlog_tokens=view.prefill_backlog_tokens,
                    requests_shed_total=view.requests_shed_total,
                    now=now)
            for view in views:
                if view.state.is_terminal():
                    autoscaler.forget(view.endpoint)

        decision = autoscaler.evaluate(len(ready), len(launching),
                                       now=now)
        op = autoscalers.AutoscalerDecisionOperator
        pool = f' [{role}]' if role else ''
        if decision.operator == op.SCALE_UP:
            want = (decision.target_num_replicas - len(ready) -
                    len(launching))
            for _ in range(max(0, want)):
                view = self.manager.spawn(
                    role=role if self.disagg else '')
                ux_utils.log(f'fleet: scale-up{pool} -> replica '
                             f'{view.replica_id} on :{view.port} '
                             f'(target '
                             f'{decision.target_num_replicas}).')
        elif decision.operator == op.SCALE_DOWN:
            excess = (len(ready) + len(launching) -
                      decision.target_num_replicas)
            for view in self._pick_victims(launching + ready, excess):
                ux_utils.log(f'fleet: scale-down{pool} -> draining '
                             f'replica {view.replica_id} (target '
                             f'{decision.target_num_replicas}).')
                self.drain_replica(view)

    def safe_tick(self) -> bool:  # stpu: entry[watcher]
        """One guarded tick for the control loop: failures are
        counted (`skypilot_fleet_tick_errors_total`) and escalated
        after 3 consecutive strikes (error log + the
        controller-degraded gauge) instead of one forever-identical
        log line per failure. A success resets the fuse. Returns
        whether the tick succeeded."""
        try:
            self.tick()
        except Exception as e:  # pylint: disable=broad-except
            self.consecutive_tick_failures += 1
            self._tick_errors.inc()
            if self.consecutive_tick_failures >= \
                    _TICK_FAILURE_STRIKES:
                self._degraded.set(1)
                ux_utils.error(
                    f'fleet: {self.consecutive_tick_failures} '
                    f'consecutive tick failures (latest: {e}); '
                    f'controller DEGRADED — replicas keep serving '
                    f'but scaling/routing updates are stalled.')
            else:
                ux_utils.log(f'fleet tick failed: {e}')
            return False
        if self.consecutive_tick_failures >= _TICK_FAILURE_STRIKES:
            ux_utils.log('fleet: tick recovered; controller no '
                         'longer degraded.')
            self._degraded.set(0)
        self.consecutive_tick_failures = 0
        return True

    def run(self) -> None:  # stpu: entry[watcher]
        """Tick until shutdown() (the serve_fleet entrypoint's main
        loop)."""
        while not self._shutdown.is_set():
            self.safe_tick()
            self._shutdown.wait(self.interval_s)

    def wait_ready(self, count: int, timeout_s: float = 300.0,  # stpu: entry[watcher]
                   poll_s: float = 0.2) -> bool:
        """Block until `count` replicas are READY (spawn-time helper
        for benches and the entrypoint). Runs on the injected clock
        like every other controller path (virtual-clock tests drive
        it without sleeping)."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            self.tick()
            if len(self.manager.ready_endpoints()) >= count:
                return True
            if self._shutdown.wait(poll_s):
                return False
        return False

    def shutdown(self) -> None:
        self._shutdown.set()
        for thread in self._drain_threads:
            thread.join(self.manager.drain_grace_s + 5.0)
        self.manager.shutdown()
