"""Durable fleet journal: the control plane's crash-only memory.

`FleetController`/`ReplicaManager` hold all fleet state in memory;
without a journal, a controller crash orphans every live `serve_lm`
process — no routing, no way to reattach, and the only recourse is
killing healthy replicas that were mid-stream. The journal fixes
that with the cheapest durable structure there is: an append-only
JSONL file of replica lifecycle events, fsync'd per append (events
are rare — spawns and state TRANSITIONS, never per-scrape), so the
last journaled state survives a SIGKILL of the controller at any
instruction.

Event grammar (one JSON object per line):

  {"event": "spawn",     ...full ReplicaRecord fields...}
  {"event": "snapshot",  ...full ReplicaRecord fields...}   # compaction
  {"event": "state",     "replica_id": N, "state": "READY", "ts": ...}
  {"event": "terminate", "replica_id": N, "ts": ...}

Replay folds the event stream into the last-known `ReplicaRecord`
per replica and DROPS terminal ones (FAILED/SHUTDOWN/terminated):
what remains is exactly the set of processes that may still be
alive and serving — the adoption candidates (`ReplicaManager.adopt`
verifies each by pid liveness + the `/stats`-echoed instance UUID,
which defeats pid/port reuse).

Crash safety:
  - a torn final line (controller died mid-append) is detected by
    the JSON parse failing and ignored — every *complete* line is
    intact because appends are written whole and fsync'd;
  - compaction never rewrites in place: the live records are
    written as `snapshot` events to a temp file in the same
    directory, fsync'd, and atomically renamed over the journal
    (readers see either the old file or the new one, never a mix);
  - replaying a compacted journal yields a byte-identical state map
    to replaying the original (tested).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, Optional

from skypilot_tpu.utils import ux_utils

#: Lifecycle states that end a replica's story: a record left in one
#: of these (or explicitly terminated) is not an adoption candidate.
_TERMINAL_STATES = frozenset(('FAILED', 'SHUTDOWN'))

#: Full-record events (create/overwrite on replay).
_RECORD_EVENTS = frozenset(('spawn', 'snapshot'))


@dataclasses.dataclass
class ReplicaRecord:
    """One replica's last journaled state — everything adoption
    needs to find, verify, and reattach (or drain) the process."""
    replica_id: int
    port: int
    endpoint: str
    instance_uuid: str
    state: str
    pid: Optional[int] = None
    # Disaggregated pool membership ('' = unified/decode-only fleet;
    # pre-role journals replay with the default).
    role: str = ''
    # Spot placement: the zone this replica models and its hourly
    # price (zero for on-demand / zoneless fleets). Journals written
    # before these fields replay with the defaults — a restarted
    # controller adopts old replicas as zoneless rather than
    # refusing the journal.
    zone: str = ''
    price_per_hour: float = 0.0

    def to_fields(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_fields(cls, fields: Dict[str, Any]) -> 'ReplicaRecord':
        return cls(replica_id=int(fields['replica_id']),
                   port=int(fields['port']),
                   endpoint=str(fields['endpoint']),
                   instance_uuid=str(fields.get('instance_uuid', '')),
                   state=str(fields.get('state', 'STARTING')),
                   pid=(int(fields['pid'])
                        if fields.get('pid') is not None else None),
                   role=str(fields.get('role', '')),
                   zone=str(fields.get('zone', '')),
                   price_per_hour=float(
                       fields.get('price_per_hour', 0.0) or 0.0))


class FleetJournal:
    """Append-only, fsync'd JSONL journal with atomic compaction.

    Thread-safe: the manager's scrape pass, drain threads, and the
    controller tick all append through one lock. The file handle is
    opened lazily and kept open between appends (one open + fsync
    per event, not per byte)."""

    def __init__(self, path: str, compact_every: int = 512) -> None:
        self.path = os.path.abspath(path)
        self.compact_every = compact_every
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None
        self._appends_since_compact = 0

    # -- writing ---------------------------------------------------------
    def append(self, event: str, **fields: Any) -> None:
        """Durably append one event: the call returns only after the
        line is on disk (write + flush + fsync). Auto-compacts every
        `compact_every` appends so a long-running fleet's journal
        stays bounded by live-replica count, not uptime."""
        record = {'event': event, 'ts': time.time()}
        record.update(fields)
        line = json.dumps(record, sort_keys=True) + '\n'
        with self._lock:
            self._append_line_locked(line)
            self._appends_since_compact += 1
            if self._appends_since_compact >= self.compact_every:
                self._compact_locked()

    def _append_line_locked(self, line: str) -> None:
        if self._fh is None:
            # Text append mode: a crash between open and write leaves
            # the file unchanged; a crash mid-write leaves a torn
            # final line replay ignores.
            self._fh = open(self.path, 'a', encoding='utf-8')
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())

    # -- replay ----------------------------------------------------------
    def replay(self) -> Dict[int, ReplicaRecord]:
        """Fold the journal into live records (terminal ones
        dropped). Tolerates a torn final line and skips (with a log)
        any malformed interior line rather than refusing to start —
        a crash-only control plane must come up from whatever the
        crash left behind."""
        return replay_journal(self.path)

    # -- compaction ------------------------------------------------------
    def compact(self) -> None:
        """Rewrite the journal as one `snapshot` line per live
        record, atomically (temp file + fsync + rename). State after
        replay is identical before and after."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        live = replay_journal(self.path)
        tmp = f'{self.path}.compact.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            for rid in sorted(live):
                record = {'event': 'snapshot', 'ts': time.time()}
                record.update(live[rid].to_fields())
                f.write(json.dumps(record, sort_keys=True) + '\n')
            f.flush()
            os.fsync(f.fileno())
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        # fsync the directory so the rename itself is durable.
        dir_fd = os.open(os.path.dirname(self.path), os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self._appends_since_compact = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def replay_journal(path: str) -> Dict[int, ReplicaRecord]:
    """Module-level replay (adoption reads the journal of a DEAD
    controller — no FleetJournal instance needed)."""
    records: Dict[int, ReplicaRecord] = {}
    terminated = set()
    try:
        with open(path, 'r', encoding='utf-8') as f:
            lines = f.readlines()
    except FileNotFoundError:
        return {}
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError as e:
            if i == len(lines) - 1:
                # Torn final line: the controller died mid-append.
                # Everything before it is intact (fsync-per-line).
                ux_utils.log(f'fleet journal {path}: ignoring torn '
                             f'final line ({e}).')
            else:
                ux_utils.error(f'fleet journal {path}: skipping '
                               f'malformed line {i + 1} ({e}).')
            continue
        name = event.get('event')
        try:
            if name in _RECORD_EVENTS:
                rec = ReplicaRecord.from_fields(event)
                records[rec.replica_id] = rec
                terminated.discard(rec.replica_id)
            elif name == 'state':
                rid = int(event['replica_id'])
                if rid in records:
                    records[rid].state = str(event['state'])
            elif name == 'terminate':
                terminated.add(int(event['replica_id']))
            else:
                ux_utils.log(f'fleet journal {path}: unknown event '
                             f'{name!r} at line {i + 1}; skipped.')
        except (KeyError, TypeError, ValueError) as e:
            ux_utils.error(f'fleet journal {path}: bad {name!r} '
                           f'event at line {i + 1} ({e}); skipped.')
    return {rid: rec for rid, rec in records.items()
            if rid not in terminated and
            rec.state not in _TERMINAL_STATES}


def max_journaled_id(path: str) -> int:
    """Highest replica id the journal has EVER named (including
    terminated ones): the restarted manager resumes its id counter
    above this so replica ids stay unique across controller
    generations (id reuse would make journal replay ambiguous)."""
    highest = 0
    try:
        with open(path, 'r', encoding='utf-8') as f:
            for line in f:
                try:
                    event = json.loads(line)
                except ValueError:
                    continue  # torn/malformed: replay already logs
                rid = event.get('replica_id')
                if isinstance(rid, int):
                    highest = max(highest, rid)
    except FileNotFoundError:
        return 0
    return highest
