"""Serve controller process: replica manager + autoscaler + LB.

Reference: sky/serve/service.py spawns controller.py (autoscaler loop,
replica manager) and load_balancer.py as processes; here both run in
one process — a reconcile thread and an aiohttp reverse proxy — since
the controller is itself cheap.

Replica contract: each replica is a normal cluster named
`<service>-rep<N>`; its task gets `SKYPILOT_SERVE_PORT` injected and
must serve HTTP on it. Readiness = spec's probe against
`http://<head_ip>:<port><readiness_path>`.
"""
from __future__ import annotations

import argparse
import asyncio
import os
import signal
import socket
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

import requests as requests_lib
from aiohttp import ClientSession, ClientTimeout, web

from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_state
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.registry import LB_POLICY_REGISTRY

_RECONCILE_SECONDS = float(os.environ.get('SKYPILOT_SERVE_RECONCILE_SECONDS',
                                          '5'))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


class ServeController:

    def __init__(self, service_name: str) -> None:
        record = serve_state.get_service(service_name)
        assert record is not None, service_name
        self.name = service_name
        self.task_config = record['task_config']
        self.spec = spec_lib.SkyServiceSpec.from_yaml_config(record['spec'])
        self.version = record['version']
        self.autoscaler = autoscalers.Autoscaler.make(self.spec)
        policy_cls = LB_POLICY_REGISTRY.from_str(
            self.spec.load_balancing_policy)
        self.policy: lb_policies.LoadBalancingPolicy = policy_cls()
        self._shutdown = threading.Event()
        self._launching: Dict[int, threading.Thread] = {}
        self._replica_ports: Dict[int, int] = {}
        # Spot serving: per-replica procurement metadata and the
        # preemption-history placer (reference: spot_placer.py:254,
        # wired through replica_managers.py:610). Rebuilt from the
        # serve DB so a controller restart keeps its spot/on-demand
        # accounting instead of double-launching.
        self._replica_meta: Dict[int, Dict] = {}
        try:
            live = {r['replica_id']
                    for r in serve_state.get_replicas(service_name)
                    if not r['status'].is_terminal()}
            self._replica_meta = {
                rid: m
                for rid, m in serve_state.get_replica_meta(
                    service_name).items() if rid in live}
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'Service {service_name}: replica metadata '
                         f'unreadable ({e}); starting with none.')
        self._spot_placer = None
        self._spot_requested = self._task_wants_spot()

    def _task_wants_spot(self) -> bool:
        try:
            task = task_lib.Task.from_yaml_config(dict(self.task_config))
            return any(r.use_spot for r in task.resources)
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'Service {self.name}: could not parse task '
                         f'config for spot detection ({e}); assuming '
                         f'on-demand.')
            return False

    def _placer(self):
        """Lazily build the spot placer from the launchable candidates."""
        if not self._spot_requested:
            return None
        if self._spot_placer is None:
            from skypilot_tpu import optimizer as optimizer_lib
            from skypilot_tpu.serve import spot_placer as placer_lib
            task = task_lib.Task.from_yaml_config(dict(self.task_config))
            locations = []
            try:
                cands = optimizer_lib.Optimizer._enumerate_candidates(  # pylint: disable=protected-access
                    task, None)
                for cand, _cost, _secs in cands:
                    if not cand.use_spot or cand.cloud is None:
                        continue
                    loc = (cand.cloud.canonical_name(), cand.region or '',
                           cand.zone)
                    if loc not in locations:
                        locations.append(loc)
            except Exception as e:  # pylint: disable=broad-except
                ux_utils.log(f'Service {self.name}: spot-candidate '
                             f'enumeration failed ({e}); dynamic spot '
                             f'placement disabled.')
            if locations:
                self._spot_placer = placer_lib.DynamicFallbackSpotPlacer(
                    locations[:16])
        return self._spot_placer

    # -- replica lifecycle ---------------------------------------------------
    def _replica_cluster(self, replica_id: int) -> str:
        return f'{self.name}-rep{replica_id}'

    def _refresh_service_record(self) -> None:
        """Pick up `serve update`s: version bump → new task/spec.

        Rolling semantics (reference: replica_managers.py:1528): new
        replicas launch at the new version; old-version replicas are
        culled only once enough new-version replicas are READY.
        """
        record = serve_state.get_service(self.name)
        if record is None:
            return
        if record['version'] != self.version:
            ux_utils.log(f'Service {self.name}: rolling to '
                         f'v{record["version"]}.')
            self.version = record['version']
            self.task_config = record['task_config']
            self.spec = spec_lib.SkyServiceSpec.from_yaml_config(
                record['spec'])
            # Autoscaler target carries over; spec swap re-reads limits.
            self.autoscaler.spec = self.spec

    def _spawn_launch(self, force_ondemand: bool) -> int:
        """Allocate a replica id + record meta synchronously, then
        launch in a thread (the synchronous meta insert keeps the
        spot/on-demand accounting race-free within one reconcile)."""
        rid = serve_state.next_replica_id(self.name)
        self._replica_meta[rid] = {
            'use_spot': self._spot_requested and not force_ondemand,
            'location': None, 'weight': 1.0, 'counted_active': False}
        thread = threading.Thread(target=self._launch_replica,
                                  args=(rid, self.version, force_ondemand),
                                  daemon=True)
        serve_state.add_replica(self.name, rid,
                                self._replica_cluster(rid), self.version)
        serve_state.set_replica_meta(self.name, rid, self._replica_meta[rid])
        self._launching[rid] = thread
        thread.start()
        return rid

    def _launch_replica(self, replica_id: int, version: int,
                        force_ondemand: bool = False) -> None:
        del version
        cluster = self._replica_cluster(replica_id)
        port = self.spec.port or _free_port()
        self._replica_ports[replica_id] = port
        task = task_lib.Task.from_yaml_config(dict(self.task_config))
        task.service = None
        task.update_envs({'SKYPILOT_SERVE_PORT': str(port)})

        # Spot placement: steer toward locations without recent
        # preemptions; when all candidates are hot (or the autoscaler
        # asked for an on-demand replica), drop use_spot.
        location = None
        use_spot = self._spot_requested and not force_ondemand
        placer = self._placer() if use_spot else None
        if use_spot and placer is not None:
            if placer.all_hot():
                ux_utils.log(
                    f'Replica {replica_id}: every spot location preempted '
                    'recently; launching on-demand instead.')
                use_spot = False
            else:
                location = placer.select()
                cloud, region, zone = location
                task.set_resources({
                    r.copy(infra='/'.join(
                        p for p in (cloud, region, zone or '') if p))
                    for r in task.resources})
        if self._spot_requested and not use_spot:
            task.set_resources({r.copy(use_spot=False)
                                for r in task.resources})
        self._replica_meta[replica_id] = {
            'use_spot': use_spot, 'location': location, 'weight': 1.0,
            'counted_active': False}
        serve_state.set_replica_meta(self.name, replica_id,
                                     self._replica_meta[replica_id])
        try:
            _, handle = execution.launch(task, cluster_name=cluster,
                                         detach_run=True,
                                         _quiet_optimizer=True)
            assert handle is not None
            head = handle.cluster_info.get_head_instance()
            endpoint = f'{head.get_feasible_ip()}:{port}'
            meta = self._replica_meta[replica_id]
            meta['weight'] = float(handle.num_hosts)
            meta['endpoint'] = endpoint
            # Hardware class for the instance-aware autoscaler (mixed
            # fleets normalize load by per-replica QPS capacity).
            launched = handle.launched_resources
            if launched is not None and launched.accelerators:
                meta['accelerator'] = next(iter(launched.accelerators))
            elif launched is not None and launched.instance_type:
                meta['accelerator'] = launched.instance_type
            serve_state.set_replica_meta(self.name, replica_id, meta)
            serve_state.set_replica_status(self.name, replica_id,
                                           serve_state.ReplicaStatus.STARTING,
                                           endpoint=endpoint)
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.error(f'Replica {replica_id} launch failed: {e}')
            if location is not None and placer is not None:
                placer.handle_preemption(location)
            # Drop the meta entry: a FAILED replica must not count
            # toward the spot/on-demand mix accounting.
            self._replica_meta.pop(replica_id, None)
            serve_state.set_replica_status(self.name, replica_id,
                                           serve_state.ReplicaStatus.FAILED)

    def _terminate_replica(self, replica_id: int, preempted: bool = False
                           ) -> None:
        meta = self._replica_meta.pop(replica_id, None)
        if meta and meta.get('location') and meta['counted_active'] and \
                self._spot_placer is not None and not preempted:
            self._spot_placer.handle_release(meta['location'])
        cluster = self._replica_cluster(replica_id)
        # Drain-before-kill: DRAINING marks the replica out of the
        # routing set while its in-flight requests finish (the
        # replica's own SIGTERM drain flips /readyz to 503); only
        # then does teardown start. Status surfaces distinguish
        # "draining" (still completing requests) from "shutting
        # down" (teardown issued) and the terminal states.
        serve_state.set_replica_status(
            self.name, replica_id, serve_state.ReplicaStatus.DRAINING)
        endpoint = None
        for replica in serve_state.get_replicas(self.name):
            if replica['replica_id'] == replica_id:
                endpoint = replica.get('endpoint')
        if endpoint is not None:
            # Stop routing NOW, not at the next reconcile: a request
            # proxied to a replica whose cluster teardown has started
            # is a guaranteed 502.
            self.policy.set_ready_replicas(
                [r for r in self.policy.ready_replicas
                 if r != endpoint])
        # The replica stays DRAINING through the teardown call: that
        # is the window where its serve_lm process is finishing
        # in-flight requests under its SIGTERM drain grace.
        from skypilot_tpu import core
        try:
            core.down(cluster)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.error(f'Replica {replica_id} teardown failed: {e}')
        if preempted:
            serve_state.remove_replica(self.name, replica_id)
        else:
            serve_state.set_replica_status(
                self.name, replica_id, serve_state.ReplicaStatus.SHUTDOWN)

    # -- probing ----------------------------------------------------------------
    def _probe_replica(self, replica: Dict) -> bool:
        endpoint = replica.get('endpoint')
        if not endpoint:
            return False
        url = f'http://{endpoint}{self.spec.readiness_path}'
        try:
            if self.spec.post_data is not None:
                resp = requests_lib.post(
                    url, json=self.spec.post_data,
                    timeout=self.spec.readiness_timeout_seconds)
            else:
                resp = requests_lib.get(
                    url, timeout=self.spec.readiness_timeout_seconds)
            return resp.status_code == 200
        except requests_lib.RequestException:
            return False

    # -- reconcile loop ----------------------------------------------------------
    def reconcile_once(self, now: Optional[float] = None) -> None:
        # `now` is injectable (virtual-clock tests / simulators);
        # defaults to the wall clock.
        now = now if now is not None else time.time()
        self._refresh_service_record()
        replicas = serve_state.get_replicas(self.name)
        S = serve_state.ReplicaStatus

        # Reap finished launch threads.
        for rid, thread in list(self._launching.items()):
            if not thread.is_alive():
                del self._launching[rid]

        ready: List[Dict] = []
        launching = 0
        for replica in replicas:
            rid = replica['replica_id']
            status: serve_state.ReplicaStatus = replica['status']
            if status in (S.DRAINING, S.SHUTTING_DOWN, S.SHUTDOWN,
                          S.FAILED):
                continue
            if status in (S.PENDING, S.PROVISIONING):
                launching += 1
                continue
            # STARTING / READY / NOT_READY: check cluster + probe.
            cluster_record = global_state.get_cluster(
                self._replica_cluster(rid))
            if cluster_record is None and rid not in self._launching:
                # Preempted / externally killed: relaunch as new replica.
                ux_utils.log(f'Replica {rid} lost (preemption); replacing.')
                meta = self._replica_meta.pop(rid, None)
                if meta and meta.get('location') and \
                        self._spot_placer is not None:
                    self._spot_placer.handle_preemption(meta['location'])
                serve_state.set_replica_status(self.name, rid, S.PREEMPTED)
                serve_state.remove_replica(self.name, rid)
                continue
            if self._probe_replica(replica):
                if status != S.READY:
                    serve_state.set_replica_status(self.name, rid, S.READY)
                    meta = self._replica_meta.get(rid)
                    if meta and meta.get('location') and \
                            not meta['counted_active'] and \
                            self._spot_placer is not None:
                        self._spot_placer.handle_active(meta['location'])
                        meta['counted_active'] = True
                ready.append(replica)
            else:
                age = now - (replica.get('launched_at') or 0)
                if status == S.READY:
                    serve_state.set_replica_status(self.name, rid,
                                                   S.NOT_READY)
                elif status == S.STARTING and \
                        age > self.spec.initial_delay_seconds:
                    ux_utils.error(
                        f'Replica {rid} failed readiness within '
                        f'{self.spec.initial_delay_seconds}s; replacing.')
                    self._terminate_replica(rid, preempted=True)
                else:
                    launching += 1

        # Rolling update: old-version replicas don't count toward the
        # target (forcing new-version launches), and each old replica is
        # culled once a same-count of new-version replicas is READY.
        ready_ids = {r['replica_id'] for r in ready}
        ready_new = [r for r in ready if r['version'] == self.version]
        old_active = [r for r in replicas
                      if r['version'] != self.version and
                      not r['status'].is_terminal() and
                      r['status'] not in (S.DRAINING, S.SHUTTING_DOWN)]
        launching_new = sum(
            1 for r in replicas
            if r['version'] == self.version and
            not r['status'].is_terminal() and
            r['status'] not in (S.DRAINING, S.SHUTTING_DOWN) and
            r['replica_id'] not in ready_ids)

        # Autoscale against the current version only. Mixed fleets
        # (instance-aware scaler) get each ready replica's QPS
        # capacity so load is normalized by hardware.
        ready_capacities = None
        if isinstance(self.autoscaler,
                      autoscalers.InstanceAwareRequestRateAutoscaler):
            ready_capacities = [
                self.autoscaler.capacity_of(
                    self._replica_meta.get(r['replica_id'],
                                           {}).get('accelerator'))
                for r in ready_new]
        decision = self.autoscaler.evaluate(
            len(ready_new), launching_new,
            ready_capacities=ready_capacities)
        if decision.operator == \
                autoscalers.AutoscalerDecisionOperator.SCALE_UP:
            want = (decision.target_num_replicas - len(ready_new) -
                    launching_new)
            # Spot/on-demand mix: launch on-demand replicas first until
            # the fallback floor (+ dynamic back-fill) is met, spot for
            # the rest (reference: autoscalers.py:933).
            od_deficit = 0
            if isinstance(self.autoscaler,
                          autoscalers.SpotRequestRateAutoscaler):
                active_od = sum(
                    1 for m in self._replica_meta.values()
                    if not m['use_spot'])
                active_spot = sum(
                    1 for m in self._replica_meta.values() if m['use_spot'])
                mix = self.autoscaler.desired_mix(active_spot)
                od_deficit = max(0, mix.ondemand - active_od)
            for _ in range(max(0, want)):
                force_od = od_deficit > 0
                od_deficit -= 1
                self._spawn_launch(force_ondemand=force_od)
        elif decision.operator == \
                autoscalers.AutoscalerDecisionOperator.SCALE_DOWN:
            excess = (len(ready_new) + launching_new -
                      decision.target_num_replicas)

            # Dynamic on-demand back-fills retire first once spot has
            # recovered (reference: autoscalers.py:933) — but only up to
            # the actual surplus, never the configured on-demand floor.
            surplus_od_ids: set = set()
            if isinstance(self.autoscaler,
                          autoscalers.SpotRequestRateAutoscaler):
                od_replicas = [
                    rid for rid, m in self._replica_meta.items()
                    if not m['use_spot']]
                active_spot = sum(1 for m in self._replica_meta.values()
                                  if m['use_spot'])
                od_surplus = max(0, len(od_replicas) -
                                 self.autoscaler.desired_mix(
                                     active_spot).ondemand)
                # Newest back-fills go first.
                surplus_od_ids = set(
                    sorted(od_replicas, reverse=True)[:od_surplus])

            # Capacity-aware victim order for mixed fleets: the
            # instance-aware target assumes the LARGEST replicas stay
            # (its cover walk is largest-first), so retire the
            # smallest-capacity ones first — otherwise killing the one
            # v5p a 3-replica target depends on under-provisions the
            # service and oscillates terminate/launch.
            def _cap(r) -> float:
                if not isinstance(
                        self.autoscaler,
                        autoscalers.InstanceAwareRequestRateAutoscaler):
                    return 0.0
                return self.autoscaler.capacity_of(
                    self._replica_meta.get(r['replica_id'],
                                           {}).get('accelerator'))

            victims = sorted(
                (r for r in replicas
                 if r['version'] == self.version and
                 not r['status'].is_terminal() and
                 r['status'] not in (S.DRAINING, S.SHUTTING_DOWN)),
                key=lambda r: (r['replica_id'] not in surplus_od_ids,
                               r['status'] == S.READY, _cap(r),
                               -r['replica_id']))
            for replica in victims[:max(0, excess)]:
                threading.Thread(target=self._terminate_replica,
                                 args=(replica['replica_id'],),
                                 daemon=True).start()

        # Spot recovery: while dynamic on-demand back-fills serve in
        # place of preempted spot capacity, keep probing for spot.
        # Recovery replicas launch *over* the target; once READY the
        # scale-down path retires the on-demand surplus first — the
        # reference's "back-fills retire as spot recovers" behavior
        # (autoscalers.py:933).
        if isinstance(self.autoscaler,
                      autoscalers.SpotRequestRateAutoscaler) and \
                self.spec.dynamic_ondemand_fallback and self._spot_requested:
            active_spot = sum(1 for m in self._replica_meta.values()
                              if m['use_spot'])
            spot_deficit = self.autoscaler.desired_mix(
                active_spot).spot - active_spot
            placer = self._placer()
            if spot_deficit > 0 and (placer is None or not placer.all_hot()):
                for _ in range(spot_deficit):
                    self._spawn_launch(force_ondemand=False)

        # Cull old-version replicas as new ones come up (1:1, keeping
        # capacity: never drop below target while rolling).
        cullable = min(len(ready_new), len(old_active))
        for replica in sorted(old_active,
                              key=lambda r: r['replica_id'])[:cullable]:
            ux_utils.log(f'Rolling update: retiring v{replica["version"]} '
                         f'replica {replica["replica_id"]}.')
            threading.Thread(target=self._terminate_replica,
                             args=(replica['replica_id'],),
                             daemon=True).start()

        # Update LB + service status.
        self.policy.set_ready_replicas(
            [r['endpoint'] for r in ready if r.get('endpoint')])
        if hasattr(self.policy, 'set_replica_weights'):
            self.policy.set_replica_weights({
                m['endpoint']: m.get('weight', 1.0)
                for m in self._replica_meta.values()
                if m.get('endpoint')})
        service = serve_state.get_service(self.name)
        if service and not service['status'].is_terminal():
            new_status = (serve_state.ServiceStatus.READY if ready
                          else serve_state.ServiceStatus.REPLICA_INIT)
            if service['status'] != new_status:
                serve_state.set_service_status(self.name, new_status)

    def reconcile_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self.reconcile_once()
            except Exception:  # pylint: disable=broad-except
                traceback.print_exc()
            self._shutdown.wait(_RECONCILE_SECONDS)

    # -- teardown -------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown.set()
        serve_state.set_service_status(
            self.name, serve_state.ServiceStatus.SHUTTING_DOWN)
        for replica in serve_state.get_replicas(self.name):
            if not replica['status'].is_terminal():
                self._terminate_replica(replica['replica_id'])
        serve_state.set_service_status(self.name,
                                       serve_state.ServiceStatus.SHUTDOWN)

    # -- load balancer ------------------------------------------------------------
    def make_lb_app(self) -> web.Application:
        controller = self

        async def proxy(request: web.Request) -> web.StreamResponse:
            replica = controller.policy.select_replica()
            controller.autoscaler.collect_request_information(1)
            if replica is None:
                return web.json_response(
                    {'error': 'no ready replicas'}, status=503)
            url = f'http://{replica}{request.rel_url}'
            try:
                # Above the replica's 600s request-future timeout (and
                # the 630s drain grace): a long STREAMED generation
                # must not be cut mid-flight by the proxy while the
                # replica is still committing tokens.
                timeout = ClientTimeout(total=660)
                async with ClientSession(timeout=timeout) as session:
                    body = await request.read()
                    async with session.request(
                            request.method, url, data=body,
                            headers={k: v for k, v in request.headers.items()
                                     if k.lower() not in ('host',)},
                    ) as upstream:
                        resp = web.StreamResponse(
                            status=upstream.status,
                            headers={k: v
                                     for k, v in upstream.headers.items()
                                     if k.lower() not in
                                     ('transfer-encoding',)})
                        await resp.prepare(request)
                        async for chunk in upstream.content.iter_chunked(
                                64 * 1024):
                            await resp.write(chunk)
                        await resp.write_eof()
                        return resp
            except Exception as e:  # pylint: disable=broad-except
                return web.json_response(
                    {'error': f'upstream {replica}: {e}'}, status=502)
            finally:
                controller.policy.request_done(replica)
                controller.autoscaler.request_done()

        app = web.Application()
        app.router.add_route('*', '/{tail:.*}', proxy)
        return app

    def make_controller_app(self) -> web.Application:
        controller = self

        async def info(request: web.Request) -> web.Response:
            del request
            replicas = serve_state.get_replicas(controller.name)
            return web.json_response({
                'service': controller.name,
                'target_num_replicas':
                    controller.autoscaler.target_num_replicas,
                'replicas': [{
                    'replica_id': r['replica_id'],
                    'status': r['status'].value,
                    'endpoint': r.get('endpoint'),
                } for r in replicas],
            })

        app = web.Application()
        app.router.add_get('/controller/info', info)
        return app


async def _run_async(controller: ServeController, controller_port: int,
                     lb_port: int) -> None:
    lb_runner = web.AppRunner(controller.make_lb_app())
    await lb_runner.setup()
    await web.TCPSite(lb_runner, '0.0.0.0', lb_port).start()
    ctl_runner = web.AppRunner(controller.make_controller_app())
    await ctl_runner.setup()
    await web.TCPSite(ctl_runner, '127.0.0.1', controller_port).start()
    while not controller._shutdown.is_set():  # pylint: disable=protected-access
        await asyncio.sleep(0.5)
    await lb_runner.cleanup()
    await ctl_runner.cleanup()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service', required=True)
    parser.add_argument('--controller-port', type=int, required=True)
    parser.add_argument('--lb-port', type=int, required=True)
    args = parser.parse_args()

    controller = ServeController(args.service)

    def handle_term(signum, frame):  # noqa: ARG001
        threading.Thread(target=controller.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, handle_term)
    reconcile = threading.Thread(target=controller.reconcile_loop,
                                 daemon=True)
    reconcile.start()
    try:
        asyncio.run(_run_async(controller, args.controller_port,
                               args.lb_port))
    finally:
        if not controller._shutdown.is_set():  # pylint: disable=protected-access
            controller.shutdown()


if __name__ == '__main__':
    main()
