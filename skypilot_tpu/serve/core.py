"""Serve verbs (server-side entrypoints): up / status / down / update.

Reference: sky/serve/server/core.py.
"""
from __future__ import annotations

import os
import signal
import socket
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import subprocess_utils


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _spawn_controller(service_name: str, controller_port: int,
                      lb_port: int, log_path: str) -> int:
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f'{repo_root}:{env.get("PYTHONPATH", "")}'
    pid = subprocess_utils.launch_daemon(
        [sys.executable, '-m', 'skypilot_tpu.serve.service',
         '--service', service_name,
         '--controller-port', str(controller_port),
         '--lb-port', str(lb_port)],
        log_path=log_path, env=env)
    serve_state.set_service_controller(service_name, pid, controller_port,
                                       lb_port)
    return pid


def reconcile_controllers() -> int:
    """HA: respawn serve controllers whose process died.

    The managed-jobs analog of controller re-adoption: a non-terminal
    service with a dead controller gets a fresh one on the SAME ports
    (the LB endpoint clients hold stays valid); the new controller
    rebuilds its replica accounting from the serve DB (replica rows +
    persisted procurement meta). Called at API-server startup.
    """
    from skypilot_tpu.utils import ux_utils
    respawned = 0
    for record in serve_state.get_services():
        if record['status'].is_terminal():
            continue
        pid = record.get('controller_pid') or -1
        if pid > 0 and subprocess_utils.process_alive(pid):
            continue
        name = record['name']
        if record['status'] == serve_state.ServiceStatus.SHUTTING_DOWN:
            # The controller died mid-teardown: FINISH the teardown —
            # respawning would resurrect a service the user was
            # removing.
            ux_utils.log(f'Service {name}: controller died mid-teardown; '
                         'completing it.')
            try:
                down(name, purge=True)
            except Exception as e:  # pylint: disable=broad-except
                ux_utils.error(f'Teardown completion for {name}: {e}')
            continue
        if not record.get('lb_port'):
            # Crashed between add_service and the first controller
            # spawn: no ports were ever recorded, so no client holds an
            # endpoint — allocate fresh ones.
            record['controller_port'] = _free_port()
            record['lb_port'] = _free_port()
        # Replica rows stuck in PENDING/PROVISIONING belong to launch
        # threads that died with the controller; drop them so the new
        # controller's autoscaler launches replacements instead of
        # counting phantoms as in-flight forever.
        for replica in serve_state.get_replicas(name):
            if replica['status'] in (serve_state.ReplicaStatus.PENDING,
                                     serve_state.ReplicaStatus.PROVISIONING):
                ux_utils.log(
                    f'Service {name}: dropping orphaned replica '
                    f'{replica["replica_id"]} '
                    f'({replica["status"].value}).')
                from skypilot_tpu import core as sky_core
                try:
                    sky_core.down(replica['cluster_name'])
                except Exception as e:  # pylint: disable=broad-except
                    # Half-created at most — but say so: a leaked
                    # cluster is a billing surprise.
                    ux_utils.log(
                        f'Service {name}: teardown of orphaned replica '
                        f'cluster {replica["cluster_name"]} failed '
                        f'({e}); it may need a manual `stpu down`.')
                serve_state.remove_replica(name, replica['replica_id'])
        ux_utils.log(f'Service {name}: controller (pid {pid}) dead; '
                     'respawning on the same ports.')
        _spawn_controller(name, record['controller_port'],
                         record['lb_port'], record['log_path'])
        respawned += 1
    return respawned


def up(task_config: Dict[str, Any], service_name: str,
       user: Optional[str] = None) -> Dict[str, Any]:
    # Identity comes from the request context (server-derived), not the
    # client-controlled payload.
    from skypilot_tpu.utils import request_context
    user = request_context.get_request_user() or user or 'unknown'
    task = task_lib.Task.from_yaml_config(dict(task_config))
    if task.service is None:
        raise exceptions.InvalidTaskYAMLError(
            'Task YAML needs a `service:` section for `serve up`.')
    if serve_state.get_service(service_name) is not None:
        raise exceptions.ServiceNotFoundError(
            f'Service {service_name!r} already exists; use `serve update`.')
    spec = task.service.to_yaml_config()
    serve_state.add_service(service_name, task_config, spec, user)
    record = serve_state.get_service(service_name)
    assert record is not None
    controller_port, lb_port = _free_port(), _free_port()
    _spawn_controller(service_name, controller_port, lb_port,
                      record['log_path'])
    return {
        'service_name': service_name,
        'endpoint': f'http://127.0.0.1:{lb_port}',
        'lb_port': lb_port,
    }


def update(task_config: Dict[str, Any], service_name: str) -> Dict[str, Any]:
    """Rolling update: bump version; controller replaces replicas.

    Round-1 semantics: restart the controller with the new config; new
    replicas launch before old ones are culled by the autoscaler
    target (blue/green-ish). Full rolling logic tracked for later.
    """
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.ServiceNotFoundError(service_name)
    task = task_lib.Task.from_yaml_config(dict(task_config))
    if task.service is None:
        raise exceptions.InvalidTaskYAMLError('`service:` section required.')
    version = serve_state.bump_service_version(
        service_name, task_config, task.service.to_yaml_config())
    return {'service_name': service_name, 'version': version}


def status(service_names: Optional[List[str]] = None
           ) -> List[Dict[str, Any]]:
    services = serve_state.get_services()
    if service_names:
        services = [s for s in services if s['name'] in service_names]
    out = []
    for s in services:
        replicas = serve_state.get_replicas(s['name'])
        out.append({
            'name': s['name'],
            'status': s['status'].value,
            'version': s['version'],
            'endpoint': (f'http://127.0.0.1:{s["lb_port"]}'
                         if s['lb_port'] else None),
            'replicas': [{
                'replica_id': r['replica_id'],
                'status': r['status'].value,
                'endpoint': r.get('endpoint'),
                'cluster_name': r['cluster_name'],
            } for r in replicas],
        })
    return out


def down(service_name: str, purge: bool = False) -> None:
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.ServiceNotFoundError(service_name)
    pid = record.get('controller_pid') or -1
    if pid > 0 and subprocess_utils.process_alive(pid):
        os.kill(pid, signal.SIGTERM)
        deadline = time.time() + 120
        while time.time() < deadline:
            current = serve_state.get_service(service_name)
            if current is None or current['status'].is_terminal():
                break
            if not subprocess_utils.process_alive(pid):
                break
            time.sleep(1)
    else:
        # Controller already dead: clean up replicas directly.
        from skypilot_tpu import core as sky_core
        for replica in serve_state.get_replicas(service_name):
            try:
                sky_core.down(replica['cluster_name'])
            except exceptions.SkyError:
                if not purge:
                    raise
    serve_state.remove_service(service_name)
