"""Load balancing policies.

Reference: sky/serve/load_balancing_policies.py — RoundRobin (:88),
LeastLoad (:114). The replica-plane LB (serve/replica_plane/lb.py)
calls `select_replica(key=..., exclude=...)`: `key` is an optional
routing key (the prefix-cache chain-key hash of the request, see
inference/affinity.py) and `exclude` removes replicas that already
failed this request (retry-on-death). Policies that ignore keys
simply route as before.
"""
from __future__ import annotations

import bisect
import collections
import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Set

from skypilot_tpu.utils.registry import LB_POLICY_REGISTRY


class LoadBalancingPolicy:

    def __init__(self) -> None:
        self.ready_replicas: List[str] = []
        self._lock = threading.Lock()

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            if set(replicas) != set(self.ready_replicas):
                self._on_replicas_changed(replicas)
            self.ready_replicas = list(replicas)

    def _on_replicas_changed(self, replicas: List[str]) -> None:
        pass

    def _candidates(self, exclude: Optional[Set[str]]) -> List[str]:
        """Ready replicas minus the caller's exclusion set (replicas
        that already failed this request). Callers hold `self._lock`."""
        if not exclude:
            return self.ready_replicas
        return [r for r in self.ready_replicas if r not in exclude]

    def select_replica(self, key: Optional[str] = None,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        raise NotImplementedError

    def request_done(self, replica: str) -> None:
        pass


@LB_POLICY_REGISTRY.register(name='round_robin', default=True)
class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def _on_replicas_changed(self, replicas: List[str]) -> None:
        self._index = 0

    def select_replica(self, key: Optional[str] = None,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        del key  # round-robin ignores routing keys
        with self._lock:
            candidates = self._candidates(exclude)
            if not candidates:
                return None
            replica = candidates[self._index % len(candidates)]
            self._index += 1
            return replica


@LB_POLICY_REGISTRY.register(name='least_load')
class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._in_flight: Dict[str, int] = collections.defaultdict(int)

    def select_replica(self, key: Optional[str] = None,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        del key  # least-load ignores routing keys
        with self._lock:
            candidates = self._candidates(exclude)
            if not candidates:
                return None
            replica = min(candidates,
                          key=lambda r: self._in_flight[r])
            self._in_flight[replica] += 1
            return replica

    def request_done(self, replica: str) -> None:
        with self._lock:
            self._in_flight[replica] = max(
                0, self._in_flight[replica] - 1)


@LB_POLICY_REGISTRY.register(name='instance_aware')
class InstanceAwareLeastLoadPolicy(LeastLoadPolicy):
    """Least-load weighted by each replica's hardware capacity.

    Reference: the instance-aware policy in sky/serve — heterogeneous
    replica pools (e.g. a v5e-8 next to a v5e-4 during a rolling
    resize) should not receive equal traffic. The controller sets a
    capacity weight per endpoint (chips per replica); selection
    minimizes in_flight / weight.
    """

    def __init__(self) -> None:
        super().__init__()
        self._weights: Dict[str, float] = {}

    def set_replica_weights(self, weights: Dict[str, float]) -> None:
        with self._lock:
            self._weights = {k: max(v, 1e-6) for k, v in weights.items()}

    def select_replica(self, key: Optional[str] = None,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        del key
        with self._lock:
            candidates = self._candidates(exclude)
            if not candidates:
                return None
            replica = min(
                candidates,
                key=lambda r: self._in_flight[r] / self._weights.get(r, 1.0))
            self._in_flight[replica] += 1
            return replica


def _hash64(data: str) -> int:
    """Stable 64-bit ring position (sha256 prefix — NOT Python's
    salted hash(), which changes per process and would remap every
    key on restart)."""
    return int.from_bytes(
        hashlib.sha256(data.encode()).digest()[:8], 'big')


@LB_POLICY_REGISTRY.register(name='prefix_affinity')
class PrefixAffinityPolicy(LeastLoadPolicy):
    """Prefix-cache / session affinity via consistent hashing.

    Requests sharing a system prompt carry the same routing key (the
    PrefixCache chain-key hash of the prompt's first full KV page,
    inference/affinity.py), and the key maps through a consistent-hash
    ring to the replica that already holds those KV pages — prefill
    skips recomputation there and the fleet stores one copy per
    prefix instead of one per replica.

    Properties the tests pin down:
      - stability: while the ready set is unchanged, the same key
        always routes to the same replica;
      - minimal remap on death: the ring is per-replica virtual
        nodes, so removing a replica moves ONLY its keys (survivors
        keep theirs — their vnodes did not move);
      - saturation fallback: when the affinity target is saturated
        (in-flight cap or reported engine backlog over the threshold)
        or not ready, the request falls back to the least-loaded
        ready replica instead of queueing behind its favorite;
      - keyless requests (no full prompt page, non-generation routes)
        use plain least-load.
    """

    _VNODES = 64  # virtual nodes per replica: evens out key spread
    _PIN_MAX = 4096  # migrated-session pins kept (bounded LRU)

    def __init__(self, saturation_inflight: int = 32,
                 saturation_backlog: Optional[float] = None) -> None:
        super().__init__()
        self.saturation_inflight = saturation_inflight
        self.saturation_backlog = saturation_backlog
        self._backlog: Dict[str, float] = {}
        self._ring_points: List[int] = []
        self._ring_owners: List[str] = []
        # Session pins (live migration): affinity key -> the endpoint
        # whose pool now holds that session's migrated KV chain. A
        # pin overrides the ring — the chain moved, the ring did not
        # — until it LRU-evicts or its endpoint leaves the ready set.
        self._pins: 'collections.OrderedDict[str, str]' = \
            collections.OrderedDict()

    # -- session pins (live migration) -----------------------------------
    def pin_key(self, key: str, endpoint: str) -> None:
        """Pin `key`'s sessions to `endpoint`: the fleet controller
        calls this for every migrated-in affinity key it scrapes, so
        follow-up requests land on the replica holding the warm
        pages instead of the ring's (now-stale) owner."""
        with self._lock:
            self._pins.pop(key, None)
            self._pins[key] = endpoint
            while len(self._pins) > self._PIN_MAX:
                self._pins.popitem(last=False)

    def _pinned(self, key: str,
                live: Iterable[str]) -> Optional[str]:
        """The pin's endpoint when it is in the live set (a pin to a
        dead or excluded replica is ignored, not dropped — scrape
        blips must not unpin a warm session). Callers hold _lock."""
        pinned = self._pins.get(key)
        if pinned is not None and pinned in live:
            return pinned
        return None

    # -- ring ------------------------------------------------------------
    def _on_replicas_changed(self, replicas: List[str]) -> None:
        ring = []
        for replica in set(replicas):
            for i in range(self._VNODES):
                ring.append((_hash64(f'{replica}#{i}'), replica))
        ring.sort()
        self._ring_points = [p for p, _ in ring]
        self._ring_owners = [r for _, r in ring]

    def _ring_lookup(self, key: str,
                     live: Iterable[str]) -> Optional[str]:
        """First live owner clockwise from the key's ring position.
        Walking (rather than filtering the ring) is what makes
        exclusion minimal-movement too: keys whose owner is live
        never move."""
        if not self._ring_points:
            return None
        live_set = set(live)
        if not live_set:
            return None
        start = bisect.bisect_left(self._ring_points, _hash64(key))
        n = len(self._ring_owners)
        for step in range(n):
            owner = self._ring_owners[(start + step) % n]
            if owner in live_set:
                return owner
        return None

    # -- load signals ----------------------------------------------------
    def set_replica_load(self, loads: Dict[str, float]) -> None:
        """Scraped engine load per endpoint (prefill backlog tokens +
        queue depth) — the saturation + fallback signal."""
        with self._lock:
            self._backlog = dict(loads)

    def _load(self, replica: str) -> float:
        return self._backlog.get(replica, 0.0) + self._in_flight[replica]

    def _saturated(self, replica: str) -> bool:
        if self._in_flight[replica] >= self.saturation_inflight:
            return True
        return (self.saturation_backlog is not None and
                self._backlog.get(replica, 0.0) >=
                self.saturation_backlog)

    # -- selection -------------------------------------------------------
    def affinity_target(self, key: Optional[str]) -> Optional[str]:
        """The pure ring mapping for `key` over the current ready set
        (no saturation, no exclusion) — what the LB compares the
        routed replica against for the affinity-hit ratio."""
        if key is None:
            return None
        with self._lock:
            pinned = self._pinned(key, self.ready_replicas)
            if pinned is not None:
                return pinned
            return self._ring_lookup(key, self.ready_replicas)

    def select_replica(self, key: Optional[str] = None,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates(exclude)
            if not candidates:
                return None
            replica = None
            if key is not None:
                replica = self._pinned(key, candidates)
                if replica is None:
                    replica = self._ring_lookup(key, candidates)
                if replica is not None and self._saturated(replica):
                    replica = None  # fall back below
            if replica is None:
                replica = min(candidates, key=self._load)
            self._in_flight[replica] += 1
            return replica
