"""Load balancing policies.

Reference: sky/serve/load_balancing_policies.py — RoundRobin (:88),
LeastLoad (:114).
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from skypilot_tpu.utils.registry import LB_POLICY_REGISTRY


class LoadBalancingPolicy:

    def __init__(self) -> None:
        self.ready_replicas: List[str] = []
        self._lock = threading.Lock()

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            if set(replicas) != set(self.ready_replicas):
                self._on_replicas_changed(replicas)
            self.ready_replicas = list(replicas)

    def _on_replicas_changed(self, replicas: List[str]) -> None:
        pass

    def select_replica(self) -> Optional[str]:
        raise NotImplementedError

    def request_done(self, replica: str) -> None:
        pass


@LB_POLICY_REGISTRY.register(name='round_robin', default=True)
class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def _on_replicas_changed(self, replicas: List[str]) -> None:
        self._index = 0

    def select_replica(self) -> Optional[str]:
        with self._lock:
            if not self.ready_replicas:
                return None
            replica = self.ready_replicas[self._index %
                                          len(self.ready_replicas)]
            self._index += 1
            return replica


@LB_POLICY_REGISTRY.register(name='least_load')
class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._in_flight: Dict[str, int] = collections.defaultdict(int)

    def select_replica(self) -> Optional[str]:
        with self._lock:
            if not self.ready_replicas:
                return None
            replica = min(self.ready_replicas,
                          key=lambda r: self._in_flight[r])
            self._in_flight[replica] += 1
            return replica

    def request_done(self, replica: str) -> None:
        with self._lock:
            self._in_flight[replica] = max(
                0, self._in_flight[replica] - 1)


@LB_POLICY_REGISTRY.register(name='instance_aware')
class InstanceAwareLeastLoadPolicy(LeastLoadPolicy):
    """Least-load weighted by each replica's hardware capacity.

    Reference: the instance-aware policy in sky/serve — heterogeneous
    replica pools (e.g. a v5e-8 next to a v5e-4 during a rolling
    resize) should not receive equal traffic. The controller sets a
    capacity weight per endpoint (chips per replica); selection
    minimizes in_flight / weight.
    """

    def __init__(self) -> None:
        super().__init__()
        self._weights: Dict[str, float] = {}

    def set_replica_weights(self, weights: Dict[str, float]) -> None:
        with self._lock:
            self._weights = {k: max(v, 1e-6) for k, v in weights.items()}

    def select_replica(self) -> Optional[str]:
        with self._lock:
            if not self.ready_replicas:
                return None
            replica = min(
                self.ready_replicas,
                key=lambda r: self._in_flight[r] / self._weights.get(r, 1.0))
            self._in_flight[replica] += 1
            return replica
