"""Spot placer: choose spot-replica locations from preemption history.

Reference: sky/serve/spot_placer.py — DynamicFallbackSpotPlacer (:254)
tracks per-(cloud, region, zone) preemption events and steers new spot
replicas toward locations that have not recently preempted, falling
back to on-demand when every candidate is hot.
"""
from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Tuple

Location = Tuple[str, str, Optional[str]]  # (cloud, region, zone)

_PREEMPTION_COOLDOWN_SECONDS = 30 * 60


class SpotPlacer:

    def __init__(self, candidates: List[Location]) -> None:
        assert candidates, 'need at least one candidate location'
        self.candidates = list(candidates)

    def select(self) -> Location:
        raise NotImplementedError

    def handle_preemption(self, location: Location,
                          now: Optional[float] = None) -> None:
        pass

    def handle_active(self, location: Location) -> None:
        pass

    def handle_release(self, location: Location) -> None:
        pass


class DynamicFallbackSpotPlacer(SpotPlacer):
    """Prefer locations with no recent preemptions; round-robin among
    equally-cold ones; report when all are hot (caller falls back to
    on-demand)."""

    def __init__(self, candidates: List[Location]) -> None:
        super().__init__(candidates)
        self._last_preempted: Dict[Location, float] = {}
        self._active_counts: Dict[Location, int] = collections.defaultdict(
            int)

    def _is_cold(self, location: Location, now: float) -> bool:
        last = self._last_preempted.get(location)
        return last is None or now - last > _PREEMPTION_COOLDOWN_SECONDS

    def select(self, now: Optional[float] = None) -> Location:
        now = now if now is not None else time.time()
        cold = [c for c in self.candidates if self._is_cold(c, now)]
        pool = cold or self.candidates
        # Spread active replicas: fewest active first, then least
        # recently preempted.
        choice = min(pool, key=lambda c: (
            self._active_counts[c], self._last_preempted.get(c, 0.0)))
        return choice

    def all_hot(self, now: Optional[float] = None) -> bool:
        """True when every candidate preempted recently → use on-demand."""
        now = now if now is not None else time.time()
        return not any(self._is_cold(c, now) for c in self.candidates)

    def handle_preemption(self, location: Location,
                          now: Optional[float] = None) -> None:
        # `now` is injectable like select()/all_hot() so virtual-clock
        # tests and the fleet simulator stay deterministic.
        self._last_preempted[location] = (now if now is not None
                                          else time.time())
        self._active_counts[location] = max(
            0, self._active_counts[location] - 1)

    def handle_active(self, location: Location) -> None:
        self._active_counts[location] += 1

    def handle_release(self, location: Location) -> None:
        """Voluntary scale-down: free the slot, no preemption mark."""
        self._active_counts[location] = max(
            0, self._active_counts[location] - 1)
