"""Serve state: services + replicas tables.

Reference: sky/serve/serve_state.py (918 LoC).
"""
from __future__ import annotations

import enum
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu.utils import db_utils


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    SHUTDOWN = 'SHUTDOWN'
    FAILED = 'FAILED'

    def is_terminal(self) -> bool:
        return self in (ServiceStatus.SHUTDOWN, ServiceStatus.FAILED)


class ReplicaStatus(enum.Enum):
    PENDING = 'PENDING'
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'
    READY = 'READY'
    NOT_READY = 'NOT_READY'
    # Drain-before-kill: the replica is out of the routing set and
    # finishing its in-flight requests (its /readyz answers 503), but
    # the process is still alive — the dashboard/status surfaces must
    # distinguish this from SHUTTING_DOWN (teardown issued) and the
    # terminal states.
    DRAINING = 'DRAINING'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    PREEMPTED = 'PREEMPTED'
    FAILED = 'FAILED'
    SHUTDOWN = 'SHUTDOWN'

    def is_terminal(self) -> bool:
        return self in (ReplicaStatus.FAILED, ReplicaStatus.SHUTDOWN)

    @property
    def is_serving(self) -> bool:
        return self == ReplicaStatus.READY


_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS services (
    name TEXT PRIMARY KEY,
    status TEXT,
    task_config TEXT,
    spec TEXT,
    controller_pid INTEGER DEFAULT -1,
    controller_port INTEGER DEFAULT 0,
    lb_port INTEGER DEFAULT 0,
    created_at REAL,
    version INTEGER DEFAULT 1,
    log_path TEXT,
    user TEXT
);
CREATE TABLE IF NOT EXISTS replicas (
    service TEXT,
    replica_id INTEGER,
    cluster_name TEXT,
    status TEXT,
    version INTEGER,
    endpoint TEXT,
    launched_at REAL,
    PRIMARY KEY (service, replica_id)
);
"""


@functools.lru_cache(maxsize=None)
def _db_for(path: str) -> db_utils.SQLiteDB:
    return db_utils.open_db(path, _CREATE_SQL)


def _db() -> db_utils.SQLiteDB:
    return _db_for(os.path.join(constants.sky_home(), 'serve.db'))


# -- services ---------------------------------------------------------------
def add_service(name: str, task_config: Dict[str, Any],
                spec: Dict[str, Any], user: str) -> None:
    log_dir = os.path.join(constants.sky_home(), 'serve_logs')
    os.makedirs(log_dir, exist_ok=True)
    _db().execute(
        'INSERT INTO services (name, status, task_config, spec, created_at, '
        'log_path, user) VALUES (?,?,?,?,?,?,?)',
        (name, ServiceStatus.CONTROLLER_INIT.value, json.dumps(task_config),
         json.dumps(spec), time.time(),
         os.path.join(log_dir, f'{name}.log'), user))


def _decode_service(row: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(row)
    out['status'] = ServiceStatus(out['status'])
    out['task_config'] = json.loads(out['task_config'] or '{}')
    out['spec'] = json.loads(out['spec'] or '{}')
    return out


def get_service(name: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM services WHERE name=?', (name,))
    return _decode_service(row) if row else None


def get_services() -> List[Dict[str, Any]]:
    return [_decode_service(r)
            for r in _db().query('SELECT * FROM services ORDER BY name')]


def set_service_status(name: str, status: ServiceStatus) -> None:
    _db().execute('UPDATE services SET status=? WHERE name=?',
                  (status.value, name))


def set_service_controller(name: str, pid: int, controller_port: int,
                           lb_port: int) -> None:
    _db().execute(
        'UPDATE services SET controller_pid=?, controller_port=?, lb_port=? '
        'WHERE name=?', (pid, controller_port, lb_port, name))


def bump_service_version(name: str, task_config: Dict[str, Any],
                         spec: Dict[str, Any]) -> int:
    _db().execute(
        'UPDATE services SET version=version+1, task_config=?, spec=? '
        'WHERE name=?', (json.dumps(task_config), json.dumps(spec), name))
    row = _db().query_one('SELECT version FROM services WHERE name=?',
                          (name,))
    return int(row['version'])


def remove_service(name: str) -> None:
    _db().execute('DELETE FROM services WHERE name=?', (name,))
    _db().execute('DELETE FROM replicas WHERE service=?', (name,))


# -- replicas ---------------------------------------------------------------
def add_replica(service: str, replica_id: int, cluster_name: str,
                version: int) -> None:
    _db().execute(
        'INSERT OR REPLACE INTO replicas (service, replica_id, cluster_name, '
        'status, version, launched_at) VALUES (?,?,?,?,?,?)',
        (service, replica_id, cluster_name,
         ReplicaStatus.PROVISIONING.value, version, time.time()))


def _decode_replica(row: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(row)
    out['status'] = ReplicaStatus(out['status'])
    return out


def get_replicas(service: str,
                 statuses: Optional[List[ReplicaStatus]] = None
                 ) -> List[Dict[str, Any]]:
    rows = _db().query(
        'SELECT * FROM replicas WHERE service=? ORDER BY replica_id',
        (service,))
    out = [_decode_replica(r) for r in rows]
    if statuses:
        out = [r for r in out if r['status'] in statuses]
    return out


def set_replica_status(service: str, replica_id: int,
                       status: ReplicaStatus,
                       endpoint: Optional[str] = None) -> None:
    if endpoint is not None:
        _db().execute(
            'UPDATE replicas SET status=?, endpoint=? '
            'WHERE service=? AND replica_id=?',
            (status.value, endpoint, service, replica_id))
    else:
        _db().execute(
            'UPDATE replicas SET status=? WHERE service=? AND replica_id=?',
            (status.value, service, replica_id))


def remove_replica(service: str, replica_id: int) -> None:
    _db().execute('DELETE FROM replicas WHERE service=? AND replica_id=?',
                  (service, replica_id))


def set_replica_meta(service: str, replica_id: int,
                     meta: Dict[str, Any]) -> None:
    """Persist controller-side replica metadata (procurement class,
    spot location, LB weight) so a restarted controller rebuilds its
    spot/on-demand accounting instead of double-launching."""
    db = _db()
    db.add_column_if_missing('replicas', 'meta', 'TEXT')
    db.execute('UPDATE replicas SET meta=? WHERE service=? AND replica_id=?',
               (json.dumps(meta), service, replica_id))


def get_replica_meta(service: str) -> Dict[int, Dict[str, Any]]:
    db = _db()
    db.add_column_if_missing('replicas', 'meta', 'TEXT')
    out: Dict[int, Dict[str, Any]] = {}
    for row in db.query('SELECT replica_id, meta FROM replicas '
                        'WHERE service=?', (service,)):
        if row['meta']:
            meta = json.loads(row['meta'])
            if meta.get('location') is not None:
                meta['location'] = tuple(meta['location'])
            out[int(row['replica_id'])] = meta
    return out


def next_replica_id(service: str) -> int:
    row = _db().query_one(
        'SELECT MAX(replica_id) AS m FROM replicas WHERE service=?',
        (service,))
    return int(row['m'] or 0) + 1


def count_services() -> int:
    row = _db().query_one('SELECT COUNT(*) AS n FROM services', ())
    return int(row['n']) if row else 0


def count_ready_replicas(service: Optional[str] = None) -> int:
    """Replicas in serving states — the single definition shared by the
    dashboard and /api/metrics (one query, no per-service fan-out)."""
    serving = [s.value for s in ReplicaStatus if s.is_serving]
    marks = ','.join('?' * len(serving))
    if service is None:
        row = _db().query_one(
            f'SELECT COUNT(*) AS n FROM replicas WHERE status IN ({marks})',
            tuple(serving))
    else:
        row = _db().query_one(
            f'SELECT COUNT(*) AS n FROM replicas WHERE service=? '
            f'AND status IN ({marks})', (service, *serving))
    return int(row['n']) if row else 0
