"""Autoscalers: decide target replica count from load signals.

Reference: sky/serve/autoscalers.py (1310 LoC) —
RequestRateAutoscaler (:479) with upscale/downscale hysteresis
(:393), QueueLengthAutoscaler (:1094). Decisions are pure functions
of (spec, signal history, time) so they unit-test without clusters.
"""
from __future__ import annotations

import dataclasses
import enum
import math
import time
from typing import Callable, Dict, List, Optional, Tuple

from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils.registry import AUTOSCALER_REGISTRY


class AutoscalerDecisionOperator(enum.Enum):
    SCALE_UP = 'scale_up'
    SCALE_DOWN = 'scale_down'
    NO_OP = 'no_op'


@dataclasses.dataclass
class AutoscalerDecision:
    operator: AutoscalerDecisionOperator
    target_num_replicas: int


class Autoscaler:
    """Base: fixed replica count (no autoscaling).

    Every scaler is clock-injectable: pass `clock` (a `time.time`-like
    callable) and/or per-call `now`/`timestamp` values and decisions
    become pure functions of (spec, signal history, time) — unit tests
    and virtual-time simulators never sleep.
    """

    def __init__(self, spec: 'spec_lib.SkyServiceSpec',
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.spec = spec
        self.target_num_replicas = spec.min_replicas
        self._clock = clock if clock is not None else time.time
        self._upscale_candidate_since: Optional[float] = None
        self._downscale_candidate_since: Optional[float] = None

    def _now(self, now: Optional[float]) -> float:
        return now if now is not None else self._clock()

    # -- shared hysteresis + decision (used by every signal scaler) -----
    def _apply_hysteresis(self, desired: int, now: float) -> None:
        """Commit a target move only after it persisted for the
        upscale/downscale delay."""
        if desired > self.target_num_replicas:
            self._downscale_candidate_since = None
            if self._upscale_candidate_since is None:
                self._upscale_candidate_since = now
            if now - self._upscale_candidate_since >= \
                    self.spec.upscale_delay_seconds:
                self.target_num_replicas = desired
                self._upscale_candidate_since = None
        elif desired < self.target_num_replicas:
            self._upscale_candidate_since = None
            if self._downscale_candidate_since is None:
                self._downscale_candidate_since = now
            if now - self._downscale_candidate_since >= \
                    self.spec.downscale_delay_seconds:
                self.target_num_replicas = desired
                self._downscale_candidate_since = None
        else:
            self._upscale_candidate_since = None
            self._downscale_candidate_since = None

    def _decide(self, total: int) -> AutoscalerDecision:
        if total < self.target_num_replicas:
            return AutoscalerDecision(AutoscalerDecisionOperator.SCALE_UP,
                                      self.target_num_replicas)
        if total > self.target_num_replicas:
            return AutoscalerDecision(AutoscalerDecisionOperator.SCALE_DOWN,
                                      self.target_num_replicas)
        return AutoscalerDecision(AutoscalerDecisionOperator.NO_OP, total)

    @classmethod
    def make(cls, spec: 'spec_lib.SkyServiceSpec') -> 'Autoscaler':
        # Spot-fallback fields imply the spot-aware scaler: a YAML with
        # base_ondemand_fallback_replicas but the default autoscaler
        # must not silently ignore its on-demand floor.
        wants_spot_mix = bool(
            getattr(spec, 'base_ondemand_fallback_replicas', 0) or
            getattr(spec, 'dynamic_ondemand_fallback', False))
        # A dict target_qps_per_replica ({accelerator: qps}) selects
        # the instance-aware scaler (mixed v5e/v5p fleets), which also
        # carries the spot floor/backfill mix.
        if isinstance(spec.target_qps_per_replica, dict):
            return InstanceAwareRequestRateAutoscaler(spec)
        # Engine-metrics scaling needs no target_qps (its signals are
        # scraped from the replicas), so it bypasses the
        # autoscaling_enabled gate that requires one.
        if getattr(spec, 'autoscaler', None) == 'engine_metrics' and \
                spec.max_replicas > spec.min_replicas:
            return EngineMetricsAutoscaler(spec)
        if spec.autoscaling_enabled:
            chosen = AUTOSCALER_REGISTRY.get(
                getattr(spec, 'autoscaler', 'request_rate'))
            if chosen is None:
                chosen = RequestRateAutoscaler
            if wants_spot_mix and chosen is RequestRateAutoscaler:
                chosen = SpotRequestRateAutoscaler
            return chosen(spec)
        if wants_spot_mix:
            return SpotRequestRateAutoscaler(spec)
        return Autoscaler(spec)

    def collect_request_information(self, num_requests: int,
                                    timestamp: Optional[float] = None
                                    ) -> None:
        """Called on request *arrival*."""

    def request_done(self, count: int = 1) -> None:
        """Called on request *completion* (queue-based scalers use it)."""

    def evaluate(self, num_ready: int, num_launching: int,
                 now: Optional[float] = None,
                 ready_capacities: Optional[List[float]] = None
                 ) -> AutoscalerDecision:
        del now, ready_capacities  # fixed target ignores load signals
        total = num_ready + num_launching
        if total < self.target_num_replicas:
            return AutoscalerDecision(AutoscalerDecisionOperator.SCALE_UP,
                                      self.target_num_replicas)
        if total > self.target_num_replicas:
            return AutoscalerDecision(AutoscalerDecisionOperator.SCALE_DOWN,
                                      self.target_num_replicas)
        return AutoscalerDecision(AutoscalerDecisionOperator.NO_OP, total)


@AUTOSCALER_REGISTRY.register(name='request_rate', default=True)
class RequestRateAutoscaler(Autoscaler):
    """Scale on QPS per ready replica, with hysteresis delays.

    Reference: autoscalers.py:479 — target =
    ceil(qps / target_qps_per_replica), clamped to [min, max]; an
    up/down move only commits after the signal has persisted for
    upscale_delay / downscale_delay seconds.
    """

    _QPS_WINDOW_SECONDS = 60.0

    def __init__(self, spec: 'spec_lib.SkyServiceSpec',
                 clock: Optional[Callable[[], float]] = None) -> None:
        super().__init__(spec, clock)
        self._request_timestamps: List[float] = []

    # -- signal -----------------------------------------------------------
    def collect_request_information(self, num_requests: int,
                                    timestamp: Optional[float] = None
                                    ) -> None:
        now = self._now(timestamp)
        self._request_timestamps.extend([now] * num_requests)
        self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self._QPS_WINDOW_SECONDS
        self._request_timestamps = [t for t in self._request_timestamps
                                    if t >= cutoff]

    def current_qps(self, now: Optional[float] = None) -> float:
        now = self._now(now)
        self._trim(now)
        return len(self._request_timestamps) / self._QPS_WINDOW_SECONDS

    def evaluate(self, num_ready: int, num_launching: int,
                 now: Optional[float] = None,
                 ready_capacities: Optional[List[float]] = None
                 ) -> AutoscalerDecision:
        del ready_capacities  # uniform fleet: every replica equal
        now = self._now(now)
        qps = self.current_qps(now)
        assert self.spec.target_qps_per_replica is not None
        desired = math.ceil(qps / self.spec.target_qps_per_replica)
        desired = max(self.spec.min_replicas,
                      min(self.spec.max_replicas, desired))
        self._apply_hysteresis(desired, now)
        return self._decide(num_ready + num_launching)


@AUTOSCALER_REGISTRY.register(name='queue_length')
class QueueLengthAutoscaler(Autoscaler):
    """Scale on in-flight (queued) requests per ready replica.

    Reference: autoscalers.py:1094 — better signal than QPS for
    long-generation LLM serving where request cost varies wildly.
    """

    def __init__(self, spec: 'spec_lib.SkyServiceSpec',
                 target_queue_per_replica: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        super().__init__(spec, clock)
        self.target_queue_per_replica = (
            target_queue_per_replica if target_queue_per_replica
            is not None else getattr(spec, 'target_queue_per_replica',
                                     4.0))
        self._in_flight = 0

    def collect_request_information(self, num_requests: int,
                                    timestamp: Optional[float] = None
                                    ) -> None:
        del timestamp
        self._in_flight += num_requests

    def request_done(self, count: int = 1) -> None:
        self._in_flight = max(0, self._in_flight - count)

    def evaluate(self, num_ready: int, num_launching: int,
                 now: Optional[float] = None,
                 ready_capacities: Optional[List[float]] = None
                 ) -> AutoscalerDecision:
        del ready_capacities
        now = self._now(now)
        desired = math.ceil(self._in_flight / self.target_queue_per_replica)
        desired = max(self.spec.min_replicas,
                      min(self.spec.max_replicas, desired))
        self._apply_hysteresis(desired, now)
        return self._decide(num_ready + num_launching)


@dataclasses.dataclass
class ReplicaMix:
    """How many replicas of each procurement class the controller
    should be running right now."""
    spot: int
    ondemand: int


@AUTOSCALER_REGISTRY.register(name='spot_request_rate')
class SpotRequestRateAutoscaler(RequestRateAutoscaler):
    """Request-rate scaling for spot serving with on-demand fallback.

    Reference: sky/serve/autoscalers.py:933 — the target count is met
    with spot replicas; `base_ondemand_fallback_replicas` are always
    on-demand (steady floor while spot churns), and with
    `dynamic_ondemand_fallback` any spot shortfall (preemptions, no
    capacity) is temporarily back-filled with on-demand replicas that
    retire as spot recovers.
    """

    def evaluate(self, num_ready: int, num_launching: int,
                 now: Optional[float] = None,
                 ready_capacities: Optional[List[float]] = None
                 ) -> AutoscalerDecision:
        # Fixed-count specs (no target_qps) still use the spot mix:
        # fall back to the base fixed-target decision.
        if self.spec.target_qps_per_replica is None:
            return Autoscaler.evaluate(self, num_ready, num_launching)
        return super().evaluate(num_ready, num_launching, now,
                                ready_capacities)

    def desired_mix(self, num_ready_spot: int) -> ReplicaMix:
        target = self.target_num_replicas
        base_od = min(self.spec.base_ondemand_fallback_replicas, target)
        spot_target = target - base_od
        od_target = base_od
        if self.spec.dynamic_ondemand_fallback:
            od_target += max(0, spot_target - num_ready_spot)
        return ReplicaMix(spot=spot_target, ondemand=od_target)


@AUTOSCALER_REGISTRY.register(name='instance_aware')
class InstanceAwareRequestRateAutoscaler(SpotRequestRateAutoscaler):
    """Request-rate scaling over a MIXED fleet: per-accelerator QPS
    capacity, load normalized by what each ready replica can actually
    serve.

    Reference: sky/serve/autoscalers.py:605
    (InstanceAwareRequestRateAutoscaler) — selected when
    `target_qps_per_replica` is a dict {accelerator: qps}, e.g.
    {'tpu-v5e-8': 4, 'tpu-v5p-8': 10} for a v5e+v5p fleet where one
    v5p replica replaces ~2.5 v5e replicas. Subclasses the spot-mix
    scaler, so the on-demand floor + dynamic backfill compose with
    capacity normalization (the reference keeps these as separate
    classes; here mixed fleets get both).

    Scaling rule (matching the reference's):
    - qps >= sum(ready capacities): scale up by
      ceil(overflow / max capacity) above the current count (the
      largest replica class is what launches next).
    - qps < sum: walk ready capacities LARGEST FIRST until they cover
      the qps; that count is the target (retire small replicas first).
    - no ready replicas: min_replicas.
    Hysteresis delays apply as in the base scaler.
    """

    def __init__(self, spec: 'spec_lib.SkyServiceSpec',
                 clock: Optional[Callable[[], float]] = None) -> None:
        super().__init__(spec, clock)
        assert isinstance(spec.target_qps_per_replica, dict), (
            'instance_aware autoscaler needs a {accelerator: qps} dict')
        self.qps_map = {str(k): float(v)
                        for k, v in spec.target_qps_per_replica.items()}

    def capacity_of(self, accelerator: Optional[str]) -> float:
        """QPS capacity of one replica by its accelerator; unknown
        hardware is assumed as capable as the best known class (the
        conservative choice against over-scaling)."""
        if accelerator is not None and accelerator in self.qps_map:
            return self.qps_map[accelerator]
        return max(self.qps_map.values())

    def evaluate(self, num_ready: int, num_launching: int,
                 now: Optional[float] = None,
                 ready_capacities: Optional[List[float]] = None
                 ) -> AutoscalerDecision:
        now = self._now(now)
        qps = self.current_qps(now)
        max_cap = max(self.qps_map.values())
        # Launching replicas are CREDITED at the largest-class capacity
        # — otherwise every evaluation during a long TPU provision
        # re-counts the same overflow and ratchets desired up to
        # max_replicas before the first launch turns ready.
        caps = sorted(list(ready_capacities or []) +
                      [max_cap] * num_launching, reverse=True)
        total_cap = sum(caps)
        if not caps:
            # Cold start from zero replicas: observed load must still
            # produce a target (min_replicas may be 0).
            desired = max(self.spec.min_replicas,
                          math.ceil(qps / max_cap))
        elif qps >= total_cap:
            overflow = qps - total_cap
            desired = len(caps) + math.ceil(overflow / max_cap)
        elif qps <= 0:
            # Idle: honor min_replicas=0 scale-to-zero like the scalar
            # RequestRateAutoscaler's ceil(0/x) == 0 path.
            desired = 0
        else:
            desired = 0
            covered = 0.0
            for cap in caps:
                desired += 1
                covered += cap
                if covered > qps:
                    break
        desired = max(self.spec.min_replicas,
                      min(self.spec.max_replicas, desired))
        self._apply_hysteresis(desired, now)
        return self._decide(num_ready + num_launching)


@dataclasses.dataclass
class EngineSignal:
    """One replica's scraped engine pressure signals (from its JSON
    `/stats`): what the inference engine actually knows about load,
    as opposed to what the front-end counted arriving."""
    queue_depth: int = 0
    prefill_backlog_tokens: int = 0
    requests_shed_total: int = 0  # lifetime counter as scraped


@AUTOSCALER_REGISTRY.register(name='engine_metrics')
class EngineMetricsAutoscaler(Autoscaler):
    """Scale replica count from scraped ENGINE metrics, not request
    counts.

    The request-rate scalers model load as arrivals/sec, which is
    blind to request cost: forty 16-token prompts and one 4k-token
    prefill read identically. The serving engine already exports the
    real pressure signals (PRs 2/4/5): queue depth (requests waiting
    for a decode slot), prefill backlog tokens (admitted prompt
    suffix not yet prefilled — the chunked-prefill scheduler's own
    work queue), and the shed counter (admission control actively
    answering 429). A replica-plane scraper feeds them in via
    `observe()`; `evaluate()` is a pure function of (signals, time).

    Scaling rule:
      desired = max(ceil(total_queue / target_queue_per_replica),
                    ceil(total_backlog / target_backlog_per_replica))
    and while sheds are occurring within the shed window, at least
    one replica above the current fleet (a bounded queue caps the
    depth signal exactly when pressure is worst — the shed counter is
    the overflow indicator). Hysteresis delays apply as in the rate
    scalers; scale-down decisions are executed by the replica plane
    through the drain contract (mark not-ready -> stop routing ->
    SIGTERM -> wait drain), never kill-then-reroute.
    """

    _SHED_WINDOW_SECONDS = 60.0

    def __init__(self, spec: 'spec_lib.SkyServiceSpec',
                 clock: Optional[Callable[[], float]] = None,
                 target_queue_per_replica: Optional[float] = None,
                 target_backlog_per_replica: Optional[float] = None
                 ) -> None:
        super().__init__(spec, clock)
        self.target_queue_per_replica = (
            target_queue_per_replica if target_queue_per_replica
            is not None else getattr(spec, 'target_queue_per_replica',
                                     4.0))
        self.target_backlog_per_replica = (
            target_backlog_per_replica if target_backlog_per_replica
            is not None else getattr(spec,
                                     'target_backlog_per_replica',
                                     4096.0))
        self._signals: Dict[str, EngineSignal] = {}
        self._last_shed_total: Dict[str, int] = {}
        self._shed_events: List[Tuple[float, int]] = []

    # -- signal ----------------------------------------------------------
    def observe(self, replica: str, *, queue_depth: int = 0,
                prefill_backlog_tokens: int = 0,
                requests_shed_total: int = 0,
                now: Optional[float] = None) -> None:
        """One scrape of one replica. `requests_shed_total` is the
        replica's lifetime counter; deltas between scrapes become
        timestamped shed events for the rate window."""
        now = self._now(now)
        prev = self._last_shed_total.get(replica)
        if prev is not None:
            delta = requests_shed_total - prev
            if delta > 0:
                self._shed_events.append((now, delta))
        self._last_shed_total[replica] = requests_shed_total
        self._signals[replica] = EngineSignal(
            queue_depth=queue_depth,
            prefill_backlog_tokens=prefill_backlog_tokens,
            requests_shed_total=requests_shed_total)
        self._trim_sheds(now)

    def forget(self, replica: str) -> None:
        """Replica left the fleet (drained or died): drop its signals
        so a dead replica's last-known backlog cannot hold the target
        up forever. Shed events already recorded stay — the overload
        they witnessed was real."""
        self._signals.pop(replica, None)
        self._last_shed_total.pop(replica, None)

    def _trim_sheds(self, now: float) -> None:
        cutoff = now - self._SHED_WINDOW_SECONDS
        self._shed_events = [(t, n) for t, n in self._shed_events
                             if t >= cutoff]

    def shed_rate(self, now: Optional[float] = None) -> float:
        """Sheds per second over the shed window."""
        now = self._now(now)
        self._trim_sheds(now)
        return (sum(n for _, n in self._shed_events) /
                self._SHED_WINDOW_SECONDS)

    def total_queue_depth(self) -> int:
        return sum(s.queue_depth for s in self._signals.values())

    def total_backlog_tokens(self) -> int:
        return sum(s.prefill_backlog_tokens
                   for s in self._signals.values())

    # -- decision --------------------------------------------------------
    def evaluate(self, num_ready: int, num_launching: int,
                 now: Optional[float] = None,
                 ready_capacities: Optional[List[float]] = None
                 ) -> AutoscalerDecision:
        del ready_capacities  # engine signals already absorb capacity
        now = self._now(now)
        desired = max(
            math.ceil(self.total_queue_depth() /
                      self.target_queue_per_replica),
            math.ceil(self.total_backlog_tokens() /
                      self.target_backlog_per_replica))
        total = num_ready + num_launching
        if self.shed_rate(now) > 0:
            # Admission control is rejecting traffic: the bounded
            # queue caps queue_depth at its limit, so depth alone
            # under-reads pressure exactly when it is worst. Grow
            # beyond the live fleet until sheds stop.
            desired = max(desired, total + 1)
        desired = max(self.spec.min_replicas,
                      min(self.spec.max_replicas, desired))
        self._apply_hysteresis(desired, now)
        return self._decide(total)
