"""SSH keypair management for cluster access.

Reference: sky/authentication.py (557 LoC) — generates the sky key
once and registers it per-cloud; TPU-VMs take it via instance
metadata (provision/gcp/instance.py).
"""
from __future__ import annotations

import os
import stat
import subprocess
from typing import Tuple

from skypilot_tpu.utils import locks

PRIVATE_KEY_PATH = '~/.ssh/sky-key'
PUBLIC_KEY_PATH = '~/.ssh/sky-key.pub'


def get_or_generate_keys() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_contents)."""
    private = os.path.expanduser(PRIVATE_KEY_PATH)
    public = os.path.expanduser(PUBLIC_KEY_PATH)
    with locks.FileLock(private + '.lock'):
        if not os.path.exists(private):
            os.makedirs(os.path.dirname(private), exist_ok=True)
            subprocess.run(
                ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
                 '-f', private, '-C', 'skypilot_tpu'],
                check=True, capture_output=True)
            os.chmod(private, stat.S_IRUSR | stat.S_IWUSR)
    with open(public, 'r', encoding='utf-8') as f:
        return private, f.read().strip()
