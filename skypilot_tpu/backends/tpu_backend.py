"""TpuVmBackend: the real backend (provision→bootstrap→gang exec).

Reference: sky/backends/cloud_vm_ray_backend.py (6709 LoC). Structure
kept — provision-with-failover, rsync workdir, setup, codegen'd job
submission, teardown/autostop — but the execution substrate is the
host-agent mesh (agent/) instead of Ray, and a TPU slice (many hosts)
is the atomic unit of provisioning (gang = slice-atomic, reference
GangSchedulingStatus per-VM logic collapses into the TPU API).
"""
from __future__ import annotations

import os
import time
import typing
from typing import Any, Dict, List, Optional, Set, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.agent import client as agent_client
from skypilot_tpu.agent import job_lib
from skypilot_tpu.backends import backend as backend_lib
from skypilot_tpu.backends import task_codegen
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils import timeline
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.status_lib import ClusterStatus

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

_WORKDIR_EXCLUDES = ['.git', '__pycache__', '.venv', 'node_modules']


class TpuVmResourceHandle(backend_lib.ResourceHandle):
    """Picklable cluster record (reference: CloudVmRayResourceHandle)."""

    def __init__(self, *, cluster_name: str, cluster_name_on_cloud: str,
                 launched_nodes: int,
                 launched_resources: 'resources_lib.Resources',
                 cluster_info: provision_common.ClusterInfo,
                 agent_secret: Optional[str] = None) -> None:
        self.cluster_name = cluster_name
        self.cluster_name_on_cloud = cluster_name_on_cloud
        self.launched_nodes = launched_nodes
        self.launched_resources = launched_resources
        self.cluster_info = cluster_info
        # Per-cluster agent auth token; lives on the handle so a
        # cluster_info refresh (core.start) does not lose it.
        self.agent_secret = (agent_secret or
                             cluster_info.custom.get('agent_secret'))

    def get_cluster_name(self) -> str:
        return self.cluster_name

    @property
    def provider_name(self) -> str:
        return self.cluster_info.provider_name

    @property
    def head_agent_addrs(self) -> List[str]:
        """Candidate head-agent endpoints, internal IP first.

        Internal is preferred (traffic stays in the VPC); external is
        the fallback when the API server sits outside the network.
        """
        head = self.cluster_info.get_head_instance()
        port = head.agent_port or constants.AGENT_PORT
        addrs = [f'{head.internal_ip}:{port}']
        if head.external_ip and head.external_ip != head.internal_ip:
            addrs.append(f'{head.external_ip}:{port}')
        return addrs

    @property
    def head_agent_addr(self) -> str:
        return self.head_agent_addrs[0]

    def agent(self) -> agent_client.AgentClient:
        return agent_client.AgentClient(
            self.head_agent_addrs,
            secret=getattr(self, 'agent_secret', None))

    @property
    def num_hosts(self) -> int:
        return len(self.cluster_info.instances)

    def get_command_runners(self) -> List[runner_lib.CommandRunner]:
        """One runner per host, head first (reference:
        get_command_runners, cloud_vm_ray_backend.py:2243)."""
        info = self.cluster_info
        runners: List[runner_lib.CommandRunner] = []
        sandbox_dirs = info.custom.get('sandbox_dirs', {})
        for inst in info.sorted_instances():
            if info.provider_name == 'local':
                runners.append(runner_lib.LocalSandboxRunner(
                    sandbox_dirs[inst.instance_id]))
            else:
                runners.append(runner_lib.SSHCommandRunner(
                    (inst.get_feasible_ip(), inst.ssh_port),
                    ssh_user=info.ssh_user,
                    ssh_private_key=info.ssh_private_key or
                    '~/.ssh/sky-key'))
        return runners

    def __repr__(self) -> str:
        return (f'TpuVmResourceHandle({self.cluster_name!r}, '
                f'{self.launched_nodes}x {self.launched_resources}, '
                f'{self.num_hosts} hosts)')


# ---------------------------------------------------------------------------
# Provision with failover
# ---------------------------------------------------------------------------
def _render_provision_artifact(cluster_name_on_cloud: str, cloud,
                               region, zones, config) -> None:
    """Write the exact request each provision attempt sends to
    `~/.sky-tpu/generated/<cluster>.yaml` — the debug-inspectable
    artifact filling the role of the reference's rendered cluster YAML
    (sky/backends/backend_utils.py write_cluster_config): when a
    launch misbehaves, `stpu debug-dump` and a human can read what was
    actually requested, per attempt, without a debugger."""
    import yaml
    try:
        out_dir = os.path.join(constants.sky_home(), 'generated')
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f'{cluster_name_on_cloud}.yaml')
        doc = {
            'rendered_at': time.strftime('%Y-%m-%dT%H:%M:%S%z'),
            'cloud': cloud.canonical_name(),
            'region': region.name,
            'zones': [z.name for z in zones] if zones else None,
            'count': config.count,
            'tags': config.tags,
            'ports_to_open': config.ports_to_open,
            'provider_config': config.provider_config,
        }
        with open(path, 'a', encoding='utf-8') as f:
            f.write('---\n')
            yaml.safe_dump(doc, f, sort_keys=False)
    except Exception:  # pylint: disable=broad-except
        pass  # a debug artifact must never fail a launch


class RetryingProvisioner:
    """Iterate candidate zones/regions; classify errors; fail over.

    Reference: RetryingVmProvisioner (cloud_vm_ray_backend.py:789) +
    FailoverCloudErrorHandlerV2 — thousands of lines of cloud-error →
    blocklist mapping; here errors block at zone granularity and the
    caller re-optimizes across clouds with `blocked_resources`.
    """

    def __init__(self) -> None:
        self.failover_history: List[Exception] = []

    @timeline.event
    def provision_with_retries(
        self, task: 'task_lib.Task',
        to_provision: 'resources_lib.Resources',
        cluster_name: str, cluster_name_on_cloud: str,
        blocked_resources: Optional[Set['resources_lib.Resources']] = None,
    ) -> Tuple[provision_common.ProvisionRecord,
               'resources_lib.Resources', cloud_lib.Region]:
        cloud = to_provision.cloud
        assert cloud is not None
        regions = cloud.regions_with_offering(
            to_provision.instance_type, to_provision.accelerators,
            to_provision.use_spot, to_provision.region, to_provision.zone)
        regions = [r for r in regions
                   if not self._region_blocked(cloud, r, blocked_resources)]
        if not regions:
            raise exceptions.ResourcesUnavailableError(
                f'No region of {cloud} offers {to_provision}.',
                failover_history=self.failover_history)
        for region in regions:
            zone_iter = cloud.zones_provision_loop(
                region=region.name, num_nodes=task.num_nodes,
                instance_type=to_provision.instance_type,
                accelerators=to_provision.accelerators,
                use_spot=to_provision.use_spot)
            for zones in zone_iter:
                if to_provision.zone is not None and zones and \
                        zones[0].name != to_provision.zone:
                    continue
                deploy_vars = cloud.make_deploy_resources_variables(
                    to_provision, cluster_name_on_cloud, region, zones,
                    task.num_nodes)
                try:
                    record = self._provision_once(
                        task, to_provision, cluster_name_on_cloud, region,
                        zones, deploy_vars)
                    resolved = to_provision.copy(
                        infra=f'{cloud.canonical_name()}/{region.name}'
                              f'/{zones[0].name if zones else "*"}')
                    return record, resolved, region
                except Exception as e:  # pylint: disable=broad-except
                    zone_str = zones[0].name if zones else region.name
                    category = getattr(e, 'category', 'transient')
                    ux_utils.log(
                        f'Provisioning in {zone_str} failed '
                        f'[{category}]: '
                        f'{common_utils.format_exception(e)}')
                    self.failover_history.append(e)
                    # Best-effort cleanup of partial creations (deploy
                    # vars carry the zone the attempt targeted). A failed
                    # cleanup leaks billable resources — surface it in the
                    # cluster events so `status -v`/debug-dump show it
                    # instead of swallowing silently.
                    try:
                        provider = cloud.provisioner_module()
                        provision_lib.terminate_instances(
                            provider, cluster_name_on_cloud,
                            provider_config=deploy_vars)
                    except Exception as cleanup_err:  # pylint: disable=broad-except
                        msg = (
                            f'Cleanup after failed provision in {zone_str} '
                            f'did not complete: '
                            f'{common_utils.format_exception(cleanup_err)}. '
                            f'Resources named {cluster_name_on_cloud!r} may '
                            f'be LEAKED in {zone_str}; verify in the cloud '
                            f'console.')
                        ux_utils.log(msg)
                        try:
                            global_state.add_cluster_event(
                                cluster_name, 'provision_cleanup_failed',
                                msg)
                        except Exception:  # pylint: disable=broad-except
                            pass  # event logging must not mask failover
                    # Category-directed failover (reference:
                    # FailoverCloudErrorHandlerV2 blocklist semantics).
                    if getattr(e, 'no_failover', False):
                        raise exceptions.ResourcesUnavailableError(
                            f'Non-retryable provisioning error in '
                            f'{zone_str}: '
                            f'{common_utils.format_exception(e)}',
                            no_failover=True,
                            failover_history=self.failover_history)
                    if getattr(e, 'blocks_cloud', False):
                        # Account-level problem (credentials, billing,
                        # TOS, global VPC): no location on THIS cloud
                        # will differ, but the request may succeed on
                        # another cloud — blocked_cloud lets re-
                        # optimizing callers (managed jobs) exclude it.
                        raise exceptions.ResourcesUnavailableError(
                            f'{cloud} cannot serve this request '
                            f'(account-level error in {zone_str}): '
                            f'{common_utils.format_exception(e)}',
                            failover_history=self.failover_history,
                            blocked_cloud=cloud.canonical_name())
                    if getattr(e, 'blocks_region', False):
                        ux_utils.log(
                            f'Quota exhausted in region {region.name}; '
                            'skipping its remaining zones.')
                        break
                    continue
        raise exceptions.ResourcesUnavailableError(
            f'Failed to provision {to_provision} in all candidate '
            f'locations of {cloud}.',
            failover_history=self.failover_history)

    @staticmethod
    def _region_blocked(cloud, region: cloud_lib.Region,
                        blocked_resources) -> bool:
        """A blocked resource with a region pins out that whole region
        (the EAGER_NEXT_REGION contract); one with NO region/zone pins
        out the whole cloud (blocked_cloud account-level failures)."""
        for b in blocked_resources or ():
            if b.cloud is not None and not b.cloud.is_same_cloud(cloud):
                continue
            if b.region is None and b.zone is None and b.cloud is not None:
                return True
            if b.region is not None and b.region == region.name:
                return True
        return False

    def _provision_once(self, task: 'task_lib.Task',
                        to_provision: 'resources_lib.Resources',
                        cluster_name_on_cloud: str,
                        region: cloud_lib.Region,
                        zones: Optional[List[cloud_lib.Zone]],
                        deploy_vars: Dict[str, Any]
                        ) -> provision_common.ProvisionRecord:
        cloud = to_provision.cloud
        assert cloud is not None
        if task.volumes:
            # Validate volumes BEFORE any cloud call: a typo'd name must
            # fail with a friendly message, not a provision timeout on
            # an unresolvable claim.
            from skypilot_tpu.volumes import core as volumes_core
            for vol_name in task.volumes.values():
                if volumes_core.get(vol_name) is None:
                    raise exceptions.SkyError(
                        f'Volume {vol_name!r} not found; create it with '
                        f'`stpu volumes apply {vol_name} --size <gb>` '
                        'first.')
            if cloud.canonical_name() == 'kubernetes':
                # k8s volumes attach at POD CREATION (PVC volumeMounts
                # in the pod spec), unlike GCP/Local runtime attach.
                deploy_vars = {**deploy_vars,
                               'volumes': dict(task.volumes)}
        config = provision_common.ProvisionConfig(
            provider_config=deploy_vars,
            authentication_config={},
            count=task.num_nodes,
            tags={'skypilot-cluster': cluster_name_on_cloud},
            ports_to_open=to_provision.ports,
        )
        _render_provision_artifact(cluster_name_on_cloud, cloud, region,
                                   zones, config)
        provider = cloud.provisioner_module()
        record = provision_lib.run_instances(provider, region.name,
                                             cluster_name_on_cloud, config)
        if not record.provider_config:
            record.provider_config = deploy_vars
        provision_lib.wait_instances(provider, region.name,
                                     cluster_name_on_cloud, 'running',
                                     provider_config=record.provider_config)
        if to_provision.ports:
            provision_lib.open_ports(provider, cluster_name_on_cloud,
                                     to_provision.ports, deploy_vars)
        return record


# ---------------------------------------------------------------------------
# The backend
# ---------------------------------------------------------------------------
class TpuVmBackend(backend_lib.Backend[TpuVmResourceHandle]):
    NAME = 'tpuvm'

    # -- provision ------------------------------------------------------------
    def provision(self, task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool, stream_logs: bool, cluster_name: str,
                  retry_until_up: bool = False,
                  blocked_resources: Optional[
                      Set['resources_lib.Resources']] = None
                  ) -> Optional[TpuVmResourceHandle]:
        del stream_logs
        assert to_provision is not None, 'optimizer must fill best_resources'
        cloud = to_provision.cloud
        assert cloud is not None
        max_len = cloud.max_cluster_name_length()
        cluster_name_on_cloud = common_utils.make_cluster_name_on_cloud(
            cluster_name, max_length=max_len or 35)

        if dryrun:
            ux_utils.log(f'Dryrun: would provision {task.num_nodes}x '
                         f'{to_provision} as {cluster_name!r} '
                         f'({cluster_name_on_cloud} on the cloud).')
            return None

        backoff = common_utils.Backoff(initial=10, max_backoff=300)
        while True:
            provisioner = RetryingProvisioner()
            try:
                record, resolved, region = \
                    provisioner.provision_with_retries(
                        task, to_provision, cluster_name,
                        cluster_name_on_cloud,
                        blocked_resources=blocked_resources)
                break
            except exceptions.ResourcesUnavailableError as e:
                # blocked_cloud: the request is pinned to this cloud at
                # this layer, so spinning on it cannot succeed — raise
                # and let a re-optimizing caller pick another cloud.
                if e.no_failover or e.blocked_cloud or not retry_until_up:
                    raise
                wait = backoff.current_backoff()
                ux_utils.log(f'Retrying provisioning in {wait:.0f}s '
                             '(--retry-until-up).')
                time.sleep(wait)

        provider = cloud.provisioner_module()
        cluster_info = provision_lib.get_cluster_info(
            provider, region.name, cluster_name_on_cloud,
            record.provider_config)
        # Per-cluster agent secret: the local provisioner mints its own
        # (exposed via cluster_info.custom); cloud paths mint one here
        # and instance_setup installs it on every host.
        agent_secret = cluster_info.custom.get('agent_secret')
        if agent_secret is None:
            import secrets as secrets_lib
            agent_secret = secrets_lib.token_hex(16)
        handle = TpuVmResourceHandle(
            cluster_name=cluster_name,
            cluster_name_on_cloud=cluster_name_on_cloud,
            launched_nodes=task.num_nodes,
            launched_resources=resolved,
            cluster_info=cluster_info,
            agent_secret=agent_secret)
        global_state.add_or_update_cluster(cluster_name, handle,
                                           requested_resources=task.resources,
                                           ready=False)
        self._bootstrap_runtime(handle)
        global_state.add_or_update_cluster(cluster_name, handle,
                                           is_launch=False, ready=True)
        ux_utils.log(f'Cluster {cluster_name!r} is UP '
                     f'({handle.num_hosts} hosts).')
        return handle

    def _bootstrap_runtime(self, handle: TpuVmResourceHandle) -> None:
        """Install + start agents on all hosts, wait healthy.

        Local provider: the provisioner already started agents.
        Cloud providers: instance_setup uploads the package and starts
        them over SSH (reference: provision/instance_setup.py).
        """
        if handle.provider_name != 'local':
            from skypilot_tpu.provision import instance_setup
            instance_setup.setup_agents(handle.cluster_info,
                                        handle.get_command_runners(),
                                        handle.cluster_name,
                                        secret=getattr(handle,
                                                       'agent_secret', None))
        if not handle.agent().wait_until_healthy(timeout=120):
            raise exceptions.ClusterSetUpError(
                f'Agent on {handle.head_agent_addr} did not become healthy.')

    def check_resources_fit_cluster(self, handle: TpuVmResourceHandle,
                                    task: 'task_lib.Task') -> None:
        for requested in task.resources:
            if requested.less_demanding_than(handle.launched_resources,
                                             task.num_nodes):
                return
        raise exceptions.ResourcesMismatchError(
            f'Requested {sorted(str(r) for r in task.resources)} does not '
            f'fit cluster {handle.cluster_name!r} '
            f'({handle.launched_nodes}x {handle.launched_resources}). '
            f'Use a matching resources spec or a new cluster.')

    # -- sync ------------------------------------------------------------------
    @timeline.event
    def sync_workdir(self, handle: TpuVmResourceHandle, workdir: str) -> None:
        workdir = os.path.expanduser(workdir)
        if not os.path.isdir(workdir):
            raise ValueError(f'workdir {workdir!r} is not a directory')
        src = workdir.rstrip('/') + '/'
        runners = handle.get_command_runners()

        def sync_one(runner: runner_lib.CommandRunner) -> None:
            runner.rsync(src, constants.SKY_REMOTE_WORKDIR + '/', up=True,
                         excludes=_WORKDIR_EXCLUDES)

        subprocess_utils.run_in_parallel(sync_one, runners)
        global_state.add_cluster_event(handle.cluster_name, 'sync_workdir',
                                       workdir)

    @timeline.event
    def sync_file_mounts(self, handle: TpuVmResourceHandle,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        runners = handle.get_command_runners()
        for dst, src in (all_file_mounts or {}).items():
            if src.startswith(('s3://', 'gs://', 'r2://', 'https://')):
                self._download_cloud_uri_on_hosts(runners, src, dst)
                continue
            src_path = os.path.expanduser(src)
            if not os.path.exists(src_path):
                raise FileNotFoundError(f'file_mount source {src!r} missing')
            suffix = '/' if os.path.isdir(src_path) else ''

            def sync_one(runner, s=src_path, d=dst, sfx=suffix):
                runner.run(f'mkdir -p {os.path.dirname(d) or "."}')
                runner.rsync(s + sfx, d + sfx, up=True)

            subprocess_utils.run_in_parallel(sync_one, runners)

        for dst, store in (storage_mounts or {}).items():
            from skypilot_tpu.data import storage as storage_lib
            storage_lib.mount_storage_on_hosts(store, dst, runners)

    @timeline.event
    def mount_volumes(self, handle: TpuVmResourceHandle,
                      volumes: Optional[Dict[str, str]]) -> None:
        """Attach + mount named volumes (reference: the provisioner
        volume ops, sky/provision/__init__.py:235-310).

        GCP: the PD attaches read-write to the head host and mounts at
        the requested path (mkfs on first use). Local: the volume dir
        is symlinked into every sandbox — the shared-disk emulation the
        e2e tests exercise.
        """
        if not volumes:
            return
        from skypilot_tpu.volumes import core as volumes_core
        provider = handle.provider_name
        runners = handle.get_command_runners()
        instances = handle.cluster_info.sorted_instances()
        for mount_path, name in volumes.items():
            record = volumes_core.get(name)
            if record is None:
                raise exceptions.SkyError(
                    f'Volume {name!r} not found; create it with '
                    f'`stpu volumes apply {name} --size <gb>` first.')
            # Relative mount paths anchor at the job's working dir
            # (where `run` commands execute); absolute/~ paths as-is.
            if not mount_path.startswith(('/', '~')):
                mount_path = f'{constants.SKY_REMOTE_WORKDIR}/{mount_path}'
            if provider == 'kubernetes':
                # Already attached at pod creation (PVC volumeMounts in
                # the pod spec); nothing to do at runtime.
                continue
            if provider == 'gcp' and \
                    handle.cluster_info.provider_config.get('tpu_vm'):
                raise exceptions.SkyError(
                    'TPU slices take disks at node creation, not at '
                    'runtime — use a GCS bucket mount (file_mounts with '
                    'gs://...) for checkpoints on TPU clusters; named '
                    'volumes attach to GCE VM and Kubernetes clusters.')
            if provider == 'local':
                for runner in runners:
                    parent = os.path.dirname(mount_path)
                    pre = f'mkdir -p {parent} && ' if parent else ''
                    device = provision_lib.attach_volume(
                        provider, record, instances[0].instance_id)
                    rc = runner.run(
                        f'{pre}ln -sfn {device} {mount_path}',
                        stream_logs=False)
                    if rc != 0:
                        raise exceptions.SkyError(
                            f'Failed to link volume {name} at '
                            f'{mount_path} (rc={rc}).')
            else:
                head_inst, head_runner = instances[0], runners[0]
                device = provision_lib.attach_volume(
                    provider, record, head_inst.instance_id)
                cmd = (
                    # attachDisk is async: wait for the device node.
                    f'for i in $(seq 1 60); do '
                    f'[ -e {device} ] && break; sleep 2; done; '
                    f'[ -e {device} ] || {{ echo "device {device} never '
                    f'appeared" >&2; exit 1; }}; '
                    f'sudo blkid {device} >/dev/null 2>&1 || '
                    f'sudo mkfs.ext4 -m 0 -F {device}; '
                    f'sudo mkdir -p {mount_path} && '
                    f'sudo mount -o discard,defaults {device} {mount_path} '
                    f'&& sudo chmod 777 {mount_path}')
                rc = head_runner.run(cmd, stream_logs=False)
                if rc != 0:
                    raise exceptions.SkyError(
                        f'Failed to mount volume {name} ({device}) at '
                        f'{mount_path} (rc={rc}).')
            global_state.add_cluster_event(
                handle.cluster_name, 'volume_mounted',
                f'{name} -> {mount_path}')

    @staticmethod
    def _download_cloud_uri_on_hosts(runners, uri: str, dst: str) -> None:
        from skypilot_tpu.data import storage as storage_lib
        cmd = storage_lib.download_command(uri, dst)

        def fetch(runner):
            rc = runner.run(cmd, stream_logs=False)
            if rc != 0:
                raise exceptions.CommandError(rc, cmd,
                                              f'failed to fetch {uri}')

        subprocess_utils.run_in_parallel(fetch, runners)

    # -- setup ------------------------------------------------------------------
    @timeline.event
    def setup(self, handle: TpuVmResourceHandle, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        if task.setup is None:
            return
        runners = handle.get_command_runners()
        env = dict(task.envs_and_secrets)
        log_dir = os.path.join(constants.logs_dir(), handle.cluster_name)

        def run_setup(args) -> int:
            idx, runner = args
            return runner.run(
                f'mkdir -p {constants.SKY_REMOTE_WORKDIR} && '
                f'cd {constants.SKY_REMOTE_WORKDIR} && '
                f'({task.setup})',
                env=env,
                stream_logs=False,
                log_path=os.path.join(log_dir, f'setup-{idx}.log'))

        rcs = subprocess_utils.run_in_parallel(run_setup,
                                               list(enumerate(runners)))
        bad = [i for i, rc in enumerate(rcs) if rc != 0]
        if bad:
            log_hint = os.path.join(log_dir, f'setup-{bad[0]}.log')
            raise exceptions.CommandError(
                rcs[bad[0]], str(task.setup),
                f'Setup failed on host(s) {bad}; see {log_hint}')
        global_state.add_cluster_event(handle.cluster_name, 'setup', '')

    # -- execute ----------------------------------------------------------------
    @timeline.event
    def execute(self, handle: TpuVmResourceHandle, task: 'task_lib.Task',
                detach_run: bool = False,
                dryrun: bool = False) -> Optional[int]:
        if dryrun:
            ux_utils.log(f'Dryrun: would execute {task.name!r} on '
                         f'{handle.cluster_name!r}.')
            return None
        if task.run is None:
            ux_utils.log('Task has no run section; skipping execution.')
            global_state.update_last_use(handle.cluster_name)
            return None
        if not isinstance(task.run, str):
            ordered = handle.cluster_info.sorted_instances()
            ips = [i.internal_ip for i in ordered]
            task = _clone_with_run(
                task, task_codegen.resolve_run_command(task, len(ordered),
                                                       ips))
        spec = task_codegen.build_job_spec(task, handle.launched_resources,
                                           handle.cluster_info)
        agent = handle.agent()
        job_id = agent.submit_job(task.name, common_utils.get_user_name(),
                                  spec)
        global_state.update_last_use(handle.cluster_name)
        ux_utils.log(f'Job {job_id} submitted to {handle.cluster_name!r} '
                     f'({len(spec["hosts"])} ranks).')
        if not detach_run:
            rc = self.tail_logs(handle, job_id, follow=True)
            del rc
        return job_id

    # -- logs / jobs --------------------------------------------------------------
    def tail_logs(self, handle: TpuVmResourceHandle, job_id: Optional[int],
                  follow: bool = True, tail: int = 0) -> int:
        agent = handle.agent()
        if job_id is None:
            jobs = agent.get_jobs(limit=1)
            if not jobs:
                ux_utils.log('No jobs on this cluster.')
                return 0
            job_id = jobs[0]['job_id']
        try:
            for line in agent.stream_job_logs(job_id, follow=follow,
                                              tail=tail):
                print(line, end='', flush=True)
        except KeyboardInterrupt:
            return 130
        job = agent.get_job(job_id)
        if job is None:
            return 1
        return 0 if job['status'] == job_lib.JobStatus.SUCCEEDED else 1

    def cancel_jobs(self, handle: TpuVmResourceHandle,
                    job_ids: Optional[list] = None,
                    cancel_all: bool = False) -> None:
        agent = handle.agent()
        if cancel_all:
            active = agent.get_jobs(status=[
                job_lib.JobStatus.PENDING, job_lib.JobStatus.INIT,
                job_lib.JobStatus.SETTING_UP, job_lib.JobStatus.RUNNING])
            job_ids = [j['job_id'] for j in active]
        for job_id in job_ids or []:
            agent.cancel_job(int(job_id))

    # -- autostop -------------------------------------------------------------
    def set_autostop(self, handle: TpuVmResourceHandle,
                     idle_minutes: Optional[int], down: bool = False) -> None:
        hook = None
        if handle.provider_name == 'local':
            # The cluster stops itself by killing its agents via the
            # provisioner (same-machine shortcut for the self-stop hook).
            import sys as _sys
            action = 'terminate' if down else 'stop'
            hook = (f'{_sys.executable} -m skypilot_tpu.provision.local.'
                    f'self_stop --cluster {handle.cluster_name_on_cloud} '
                    f'--action {action}')
        handle.agent().set_autostop(idle_minutes, down, hook)
        global_state.set_cluster_autostop(
            handle.cluster_name,
            -1 if idle_minutes is None else idle_minutes, down)

    # -- teardown ---------------------------------------------------------------
    @timeline.event
    def teardown(self, handle: TpuVmResourceHandle, terminate: bool,
                 purge: bool = False) -> None:
        provider = handle.provider_name
        try:
            if terminate:
                provision_lib.terminate_instances(
                    provider, handle.cluster_name_on_cloud,
                    handle.cluster_info.provider_config)
            else:
                if handle.launched_resources.is_tpu_slice and \
                        handle.launched_resources.slice_spec.is_pod_slice \
                        and provider == 'gcp':
                    raise exceptions.NotSupportedError(
                        'Multi-host TPU pod slices cannot be stopped; '
                        'use down (terminate).')
                provision_lib.stop_instances(
                    provider, handle.cluster_name_on_cloud,
                    handle.cluster_info.provider_config)
        except Exception:
            if not purge:
                raise
        global_state.remove_cluster(handle.cluster_name, terminate=terminate)


def _clone_with_run(task: 'task_lib.Task', run: Optional[str]):
    import copy
    clone = copy.copy(task)
    clone.run = run
    return clone
