"""Task codegen: turn a Task's run section into a gang-exec job spec.

Reference: sky/backends/task_codegen.py (1068 LoC) generates a Ray
driver (placement groups, per-node bash tasks, rank env export). The
TPU-native codegen is declarative instead of generated-program: it
produces the job spec the agent's job_driver consumes — one script +
per-rank env for every host of every slice — because a TPU slice is
already gang-allocated; no placement-group dance is needed.

Env contract (reference sky/skylet/constants.py:521-526 + JAX
multi-host additions, SURVEY §2.4):
  SKYPILOT_NODE_RANK       global host rank (0 = head). For TPU pod
                           slices there is one rank per *host*, the
                           reference's `num_ips_per_node` behavior.
  SKYPILOT_NODE_IPS        newline-separated host IPs in rank order
  SKYPILOT_NUM_NODES       total number of hosts (ranks)
  SKYPILOT_NUM_GPUS_PER_NODE  accelerator count visible per host
  SKYPILOT_TASK_ID         unique id for this run
  JAX_COORDINATOR_ADDRESS  rank-0 host ip:8476
  JAX_NUM_PROCESSES / JAX_PROCESS_ID
  TPU_WORKER_ID            host rank within its slice
  TPU_WORKER_HOSTNAMES     comma-separated host IPs of this rank's slice
  MEGASCALE_NUM_SLICES / MEGASCALE_SLICE_ID / MEGASCALE_COORDINATOR_ADDRESS
                           multislice (DCN) bootstrap, set when a task
                           spans >1 slice
"""
from __future__ import annotations

import time
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu.provision import common as provision_common

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib


def make_task_id(task_name: Optional[str]) -> str:
    ts = time.strftime('%Y%m%d-%H%M%S')
    return f'{ts}_{task_name or "task"}'


def build_job_spec(task: 'task_lib.Task',
                   launched_resources: 'resources_lib.Resources',
                   cluster_info: provision_common.ClusterInfo,
                   task_id: Optional[str] = None,
                   extra_env: Optional[Dict[str, str]] = None
                   ) -> Dict[str, Any]:
    """The spec consumed by agent.job_driver.run_job."""
    assert isinstance(task.run, str) or task.run is None, (
        'command generators resolved by caller')
    instances = cluster_info.sorted_instances()
    # Global rank order: (node_rank, host_rank); instances[0] is the head
    # but ranks are topology order — recompute explicitly.
    ordered = sorted(instances, key=lambda i: (i.node_rank, i.host_rank))
    num_ranks = len(ordered)
    head = ordered[0]
    slice_spec = launched_resources.slice_spec
    hosts_per_slice = (slice_spec.num_hosts if slice_spec is not None else 1)
    num_slices = task.num_nodes
    chips_per_host = (slice_spec.chips_per_host
                      if slice_spec is not None else 0)

    node_ips = '\n'.join(i.internal_ip for i in ordered)
    task_id = task_id or make_task_id(task.name)

    base_env: Dict[str, str] = {
        constants.TASK_ID_ENV_VAR: task_id,
        constants.NUM_NODES_ENV_VAR: str(num_ranks),
        constants.NODE_IPS_ENV_VAR: node_ips,
        constants.NUM_GPUS_PER_NODE_ENV_VAR: str(
            _gpus_per_host(launched_resources)),
        constants.JAX_COORDINATOR_ADDR_ENV_VAR:
            f'{head.internal_ip}:{constants.JAX_COORDINATOR_PORT}',
        constants.JAX_NUM_PROCESSES_ENV_VAR: str(num_ranks),
    }
    if slice_spec is not None:
        base_env[constants.TPU_ACCELERATOR_TYPE_ENV_VAR] = (
            slice_spec.gcp_accelerator_type())
    if num_slices > 1:
        base_env[constants.TPU_NUM_SLICES_ENV_VAR] = str(num_slices)
        base_env[constants.MEGASCALE_COORDINATOR_ENV_VAR] = head.internal_ip
    base_env.update(task.envs_and_secrets)
    if extra_env:
        base_env.update(extra_env)

    per_rank_env: List[Dict[str, str]] = []
    slice_hosts: Dict[int, List[str]] = {}
    for inst in ordered:
        slice_hosts.setdefault(inst.node_rank, []).append(inst.internal_ip)
    for rank, inst in enumerate(ordered):
        env = {
            constants.NODE_RANK_ENV_VAR: str(rank),
            constants.JAX_PROCESS_ID_ENV_VAR: str(rank),
            constants.TPU_WORKER_ID_ENV_VAR: str(inst.host_rank),
            constants.TPU_WORKER_HOSTNAMES_ENV_VAR: ','.join(
                slice_hosts[inst.node_rank]),
        }
        if num_slices > 1:
            env[constants.TPU_SLICE_ID_ENV_VAR] = str(inst.node_rank)
        per_rank_env.append(env)

    script = task.run or 'true'
    return {
        'task_id': task_id,
        'script': script,
        'env': base_env,
        'per_rank_env': per_rank_env,
        'cwd': constants.SKY_REMOTE_WORKDIR,
        'hosts': [{
            'addr': inst.agent_addr,
            'rank': rank,
            'instance_id': inst.instance_id,
        } for rank, inst in enumerate(ordered)],
        'num_slices': num_slices,
        'hosts_per_slice': hosts_per_slice,
        'chips_per_host': chips_per_host,
    }


def _gpus_per_host(resources: 'resources_lib.Resources') -> int:
    """GPU count per host; TPUs excluded (schedulable non-GPU
    accelerators, reference sky/utils/accelerator_registry.py:76-81)."""
    if resources.is_tpu_slice or resources.accelerators is None:
        return 0
    return next(iter(resources.accelerators.values()))


def resolve_run_command(task: 'task_lib.Task', num_ranks: int,
                        ips: List[str]) -> Optional[str]:
    """Resolve a callable run section (per-rank command generator)."""
    if task.run is None or isinstance(task.run, str):
        return task.run
    # Callable: generate rank 0's command; per-rank generators are a
    # reference feature used rarely — generate a dispatch script.
    commands = []
    for rank in range(num_ranks):
        cmd = task.run(rank, ips)
        commands.append(cmd if cmd else 'true')
    lines = ['case "$SKYPILOT_NODE_RANK" in']
    for rank, cmd in enumerate(commands):
        lines.append(f'  {rank}) {cmd} ;;')
    lines.append('esac')
    return '\n'.join(lines)
