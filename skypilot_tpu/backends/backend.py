"""Backend ABC: cluster lifecycle + job submission.

Reference: sky/backends/backend.py:24,30 — provision / sync_workdir /
sync_file_mounts / setup / execute / teardown with a per-backend
ResourceHandle.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Generic, Optional, TypeVar

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib


class ResourceHandle:
    """Opaque, picklable record of a provisioned cluster."""

    def get_cluster_name(self) -> str:
        raise NotImplementedError


_HandleType = TypeVar('_HandleType', bound=ResourceHandle)


class Backend(Generic[_HandleType]):
    NAME = 'backend'

    # --- lifecycle ----------------------------------------------------------
    def check_resources_fit_cluster(self, handle: _HandleType,
                                    task: 'task_lib.Task') -> None:
        raise NotImplementedError

    def provision(self, task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool, stream_logs: bool, cluster_name: str,
                  retry_until_up: bool = False) -> Optional[_HandleType]:
        raise NotImplementedError

    def sync_workdir(self, handle: _HandleType, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, handle: _HandleType,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def setup(self, handle: _HandleType, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        raise NotImplementedError

    def execute(self, handle: _HandleType, task: 'task_lib.Task',
                detach_run: bool = False,
                dryrun: bool = False) -> Optional[int]:
        """Submit the task; returns job_id (None for dryrun)."""
        raise NotImplementedError

    def post_execute(self, handle: _HandleType, down: bool) -> None:
        pass

    def teardown(self, handle: _HandleType, terminate: bool,
                 purge: bool = False) -> None:
        raise NotImplementedError

    # --- jobs ---------------------------------------------------------------
    def tail_logs(self, handle: _HandleType, job_id: Optional[int],
                  follow: bool = True, tail: int = 0) -> int:
        raise NotImplementedError

    def cancel_jobs(self, handle: _HandleType,
                    job_ids: Optional[list] = None,
                    cancel_all: bool = False) -> None:
        raise NotImplementedError
