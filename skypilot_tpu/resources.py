"""Resources: the hardware request attached to a Task.

Reference: sky/resources.py (3033 LoC) — cloud/region/zone, instance
type, cpus/mem, accelerators, spot, disk, ports, labels, autostop.

TPU-first differences from the reference:
  - A TPU slice (`tpu-v5p-128`) is the primary unit. It implies the
    host VM shape and host count via `utils/tpu_utils.py`; no
    hardcoded 'TPU-VM' pseudo-instance-type
    (cf. sky/clouds/gcp.py:770-823).
  - `accelerator_args` carries TPU-specific knobs: `topology`
    ("4x4x8" ICI torus), `runtime_version`, `reserved`, and
    `spot_queued` (GCP QueuedResources).
  - `slice_spec` exposes hosts/chips/ICI topology to the optimizer,
    provisioner and gang executor.
"""
from __future__ import annotations

import textwrap
from typing import Any, Dict, List, Optional, Set, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu.utils import accelerator_registry
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import infra_utils
from skypilot_tpu.utils import tpu_utils

_DEFAULT_DISK_SIZE_GB = 256


class Resources:
    """An immutable-ish hardware request; use `.copy(**overrides)`."""

    def __init__(
        self,
        cloud: Optional['clouds.Cloud'] = None,  # noqa: F821
        instance_type: Optional[str] = None,
        cpus: Union[None, int, float, str] = None,
        memory: Union[None, int, float, str] = None,
        accelerators: Union[None, str, Dict[str, int]] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        infra: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        use_spot: Optional[bool] = None,
        job_recovery: Optional[Union[str, Dict[str, Any]]] = None,
        disk_size: Optional[Union[int, str]] = None,
        disk_tier: Optional[str] = None,
        ports: Optional[Union[int, str, List[Union[int, str]]]] = None,
        image_id: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        autostop: Optional[Union[bool, int, Dict[str, Any]]] = None,
        priority: Optional[int] = None,
        _cluster_config_overrides: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._version = 1

        if infra is not None and (region is not None or zone is not None or
                                  (cloud is not None and
                                   not isinstance(cloud, str))):
            raise ValueError('Specify either `infra` or '
                             '`cloud`/`region`/`zone`, not both.')
        if infra is not None:
            info = infra_utils.InfraInfo.from_str(infra)
            cloud, region, zone = info.cloud, info.region, info.zone

        if isinstance(cloud, str):
            from skypilot_tpu.utils.registry import CLOUD_REGISTRY
            import skypilot_tpu.clouds  # noqa: F401  (registers clouds)
            cloud_cls = CLOUD_REGISTRY.from_str(cloud)
            cloud = cloud_cls() if cloud_cls is not None else None

        self._cloud = cloud
        self._region: Optional[str] = None
        self._zone: Optional[str] = None

        self._instance_type = instance_type
        self._cpus = None if cpus is None else str(cpus)
        self._memory = None if memory is None else str(memory)

        self._use_spot_specified = use_spot is not None
        self._use_spot = bool(use_spot) if use_spot is not None else False
        self._job_recovery = self._parse_job_recovery(job_recovery)

        if disk_size is None:
            self._disk_size = _DEFAULT_DISK_SIZE_GB
        else:
            self._disk_size = int(common_utils.parse_memory(disk_size))
        self._disk_tier = disk_tier
        self._ports = self._parse_ports(ports)
        self._image_id = image_id
        self._labels = dict(labels) if labels else None
        self._autostop = self._parse_autostop(autostop)
        self._priority = priority
        self._cluster_config_overrides = _cluster_config_overrides or {}

        self._accelerators = self._parse_accelerators(accelerators)
        self._accelerator_args = dict(accelerator_args or {})

        self._validate_and_set_region_zone(region, zone)
        self._validate_accelerators()

    # -- parsing helpers ----------------------------------------------------
    @staticmethod
    def _parse_accelerators(
            accelerators: Union[None, str, Dict[str, int]]
    ) -> Optional[Dict[str, int]]:
        if accelerators is None:
            return None
        if isinstance(accelerators, str):
            if ':' in accelerators:
                name, count = accelerators.split(':', 1)
                accelerators = {name.strip(): int(float(count))}
            else:
                accelerators = {accelerators.strip(): 1}
        out = {}
        for name, count in accelerators.items():
            canonical = accelerator_registry.canonicalize_accelerator_name(
                name)
            out[canonical] = int(count)
        if len(out) != 1:
            raise exceptions.InvalidResourcesError(
                f'Exactly one accelerator type per resource; got {out}.')
        return out

    @staticmethod
    def _parse_job_recovery(
            job_recovery: Optional[Union[str, Dict[str, Any]]]
    ) -> Optional[Dict[str, Any]]:
        if job_recovery is None:
            return None
        if isinstance(job_recovery, str):
            return {'strategy': job_recovery.lower()}
        out = dict(job_recovery)
        if 'strategy' in out and isinstance(out['strategy'], str):
            out['strategy'] = out['strategy'].lower()
        return out

    @staticmethod
    def _parse_ports(
            ports: Optional[Union[int, str, List[Union[int, str]]]]
    ) -> Optional[List[str]]:
        if ports is None:
            return None
        if not isinstance(ports, list):
            ports = [ports]
        out = []
        for p in ports:
            s = str(p)
            if '-' in s:
                lo, hi = s.split('-')
                int(lo), int(hi)  # validate
            else:
                int(s)
            out.append(s)
        return sorted(set(out)) or None

    @staticmethod
    def _parse_autostop(
            autostop: Optional[Union[bool, int, Dict[str, Any]]]
    ) -> Optional[Dict[str, Any]]:
        """Normalize to {'idle_minutes': int, 'down': bool} or None."""
        if autostop is None or autostop is False:
            return None
        if autostop is True:
            return {'idle_minutes': 5, 'down': False}
        if isinstance(autostop, int):
            if autostop < 0:
                return None
            return {'idle_minutes': autostop, 'down': False}
        out = {'idle_minutes': int(autostop.get('idle_minutes', 5)),
               'down': bool(autostop.get('down', False))}
        return out

    # -- validation ---------------------------------------------------------
    def _validate_and_set_region_zone(self, region: Optional[str],
                                      zone: Optional[str]) -> None:
        if region is None and zone is None:
            return
        if self._cloud is None:
            # Infer the cloud from region/zone across registered clouds.
            from skypilot_tpu.utils.registry import CLOUD_REGISTRY
            import skypilot_tpu.clouds  # noqa: F401
            candidates = []
            for cloud_cls in CLOUD_REGISTRY.values():
                cloud = cloud_cls()
                try:
                    cloud.validate_region_zone(region, zone)
                    candidates.append(cloud)
                except ValueError:
                    continue
            if not candidates:
                raise ValueError(
                    f'Invalid (region={region!r}, zone={zone!r}) for any '
                    'registered cloud.')
            if len(candidates) > 1:
                raise ValueError(
                    f'Multiple clouds match region={region!r}: '
                    f'{candidates}; specify `infra: <cloud>/{region}`.')
            self._cloud = candidates[0]
            self._region, self._zone = self._cloud.validate_region_zone(
                region, zone)
        else:
            self._region, self._zone = self._cloud.validate_region_zone(
                region, zone)

    def _validate_accelerators(self) -> None:
        accs = self._accelerators
        if accs is None:
            return
        acc_name = next(iter(accs))
        if tpu_utils.is_tpu(acc_name):
            topo = self._accelerator_args.get('topology')
            # Raises on malformed names/topologies:
            spec = tpu_utils.get_slice_spec(acc_name, topo)
            if accs[acc_name] != 1:
                raise exceptions.InvalidResourcesError(
                    f'TPU slices are atomic; use a larger slice instead of '
                    f'{accs[acc_name]}x {acc_name}.')
            if self._instance_type is not None:
                raise exceptions.InvalidResourcesError(
                    'Do not set instance_type with a TPU slice; the slice '
                    f'({spec.name}) determines the host VM shape.')

    # -- properties ---------------------------------------------------------
    @property
    def cloud(self):
        return self._cloud

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def cpus(self) -> Optional[str]:
        return self._cpus

    @property
    def memory(self) -> Optional[str]:
        return self._memory

    @property
    def accelerators(self) -> Optional[Dict[str, int]]:
        return self._accelerators

    @property
    def accelerator_args(self) -> Dict[str, Any]:
        return self._accelerator_args

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def use_spot_specified(self) -> bool:
        return self._use_spot_specified

    @property
    def job_recovery(self) -> Optional[Dict[str, Any]]:
        return self._job_recovery

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def disk_tier(self) -> Optional[str]:
        return self._disk_tier

    @property
    def ports(self) -> Optional[List[str]]:
        return self._ports

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def labels(self) -> Optional[Dict[str, str]]:
        return self._labels

    @property
    def autostop(self) -> Optional[Dict[str, Any]]:
        return self._autostop

    @property
    def priority(self) -> Optional[int]:
        return self._priority

    @property
    def cluster_config_overrides(self) -> Dict[str, Any]:
        return self._cluster_config_overrides

    @property
    def infra(self) -> infra_utils.InfraInfo:
        cloud = str(self._cloud).lower() if self._cloud else None
        return infra_utils.InfraInfo(cloud, self._region, self._zone)

    # -- TPU-specific -------------------------------------------------------
    @property
    def tpu_accelerator_name(self) -> Optional[str]:
        if self._accelerators is None:
            return None
        name = next(iter(self._accelerators))
        return name if tpu_utils.is_tpu(name) else None

    @property
    def is_tpu_slice(self) -> bool:
        return self.tpu_accelerator_name is not None

    @property
    def slice_spec(self) -> Optional[tpu_utils.TpuSliceSpec]:
        name = self.tpu_accelerator_name
        if name is None:
            return None
        return tpu_utils.get_slice_spec(
            name, self._accelerator_args.get('topology'))

    @property
    def hosts_per_node(self) -> int:
        """How many VMs/processes one Task node maps to (1 unless a pod)."""
        spec = self.slice_spec
        return spec.num_hosts if spec is not None else 1

    # -- queries ------------------------------------------------------------
    def is_launchable(self) -> bool:
        if self._cloud is None:
            return False
        if self.is_tpu_slice:
            return True
        return self._instance_type is not None

    def assert_launchable(self) -> 'Resources':
        assert self.is_launchable(), self
        return self

    def get_cost(self, seconds: float) -> float:
        """Cost in $ for holding these resources for `seconds`."""
        hours = seconds / 3600.0
        assert self._cloud is not None, 'non-launchable resources have no cost'
        hourly = self._cloud.get_hourly_cost(self)
        return hourly * hours

    def get_hourly_cost(self) -> float:
        assert self._cloud is not None
        return self._cloud.get_hourly_cost(self)

    def less_demanding_than(self, other: 'Resources',
                            requested_num_nodes: int = 1) -> bool:
        """Can `other` (an existing cluster's resources) serve `self`?

        Reference: sky/resources.py:1984.
        """
        if self._cloud is not None and not self._cloud.is_same_cloud(
                other.cloud):
            return False
        if self._region is not None and self._region != other.region:
            return False
        if self._zone is not None and self._zone != other.zone:
            return False
        if (self._instance_type is not None and
                self._instance_type != other.instance_type):
            return False
        if self._use_spot_specified and self._use_spot != other.use_spot:
            return False
        if self._accelerators is not None:
            if other.accelerators is None:
                return False
            for acc, count in self._accelerators.items():
                if other.accelerators.get(acc, 0) < count:
                    return False
            if self.is_tpu_slice:
                topo = self._accelerator_args.get('topology')
                if (topo is not None and
                        topo != other.accelerator_args.get('topology')):
                    return False
        if self._ports is not None:
            if other.ports is None:
                return False
            if not set(self._ports).issubset(set(other.ports)):
                return False
        return True

    # -- copy / serialization ----------------------------------------------
    def copy(self, **override) -> 'Resources':
        current = dict(
            cloud=self._cloud,
            instance_type=self._instance_type,
            cpus=self._cpus,
            memory=self._memory,
            accelerators=self._accelerators,
            accelerator_args=self._accelerator_args,
            region=self._region,
            zone=self._zone,
            use_spot=self._use_spot if self._use_spot_specified else None,
            job_recovery=self._job_recovery,
            disk_size=self._disk_size,
            disk_tier=self._disk_tier,
            ports=self._ports,
            image_id=self._image_id,
            labels=self._labels,
            autostop=self._autostop,
            priority=self._priority,
            _cluster_config_overrides=self._cluster_config_overrides,
        )
        if 'infra' in override:
            current.pop('cloud', None)
            current.pop('region', None)
            current.pop('zone', None)
        current.update(override)
        return Resources(**current)

    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]) -> Set['Resources']:
        """Parse the `resources:` section; may return multiple candidates.

        Supports `any_of:` / `ordered:` lists like the reference
        (sky/resources.py from_yaml_config).
        """
        if config is None:
            return {cls()}
        config = dict(config)
        any_of = config.pop('any_of', None)
        ordered = config.pop('ordered', None)
        if any_of is not None and ordered is not None:
            raise exceptions.InvalidTaskYAMLError(
                'Specify any_of or ordered, not both.')
        base = config

        def make(override: Dict[str, Any]) -> 'Resources':
            merged = {**base, **override}
            return cls._from_flat_config(merged)

        if any_of is not None:
            return {make(o) for o in any_of}
        if ordered is not None:
            # Ordered preference encoded via descending priority.
            out = set()
            for i, o in enumerate(ordered):
                r = make(o)
                r._priority = len(ordered) - i  # pylint: disable=protected-access
                out.add(r)
            return out
        return {make({})}

    @classmethod
    def _from_flat_config(cls, config: Dict[str, Any]) -> 'Resources':
        known = dict(config)
        kwargs: Dict[str, Any] = {}
        for key in ('infra', 'instance_type', 'cpus', 'memory', 'accelerators',
                    'accelerator_args', 'use_spot', 'job_recovery', 'disk_size',
                    'disk_tier', 'ports', 'image_id', 'labels', 'autostop',
                    'priority'):
            if key in known:
                kwargs[key] = known.pop(key)
        # Back-compat: cloud/region/zone as separate keys.
        for key in ('cloud', 'region', 'zone'):
            if key in known:
                kwargs[key] = known.pop(key)
        overrides = known.pop('config_overrides', None)
        if overrides is not None:
            kwargs['_cluster_config_overrides'] = overrides
        if known:
            raise exceptions.InvalidTaskYAMLError(
                f'Unknown resources fields: {sorted(known)}')
        return cls(**kwargs)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key, value):
            if value is not None:
                config[key] = value

        add('infra', self.infra.to_str())
        add('instance_type', self._instance_type)
        add('cpus', self._cpus)
        add('memory', self._memory)
        if self._accelerators is not None:
            name, count = next(iter(self._accelerators.items()))
            add('accelerators', f'{name}:{count}' if count != 1 else name)
        if self._accelerator_args:
            add('accelerator_args', self._accelerator_args)
        if self._use_spot_specified:
            config['use_spot'] = self._use_spot
        add('job_recovery', self._job_recovery)
        if self._disk_size != _DEFAULT_DISK_SIZE_GB:
            add('disk_size', self._disk_size)
        add('disk_tier', self._disk_tier)
        add('ports', self._ports)
        add('image_id', self._image_id)
        add('labels', self._labels)
        add('autostop', self._autostop)
        add('priority', self._priority)
        if self._cluster_config_overrides:
            add('config_overrides', self._cluster_config_overrides)
        return config

    # -- misc ---------------------------------------------------------------
    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        return hash(common_utils.json_dumps_compact(self.to_yaml_config()))

    def __repr__(self) -> str:
        parts = []
        if self._cloud is not None:
            parts.append(str(self._cloud))
        if self._region is not None:
            parts.append(self._region)
        if self._zone is not None:
            parts.append(self._zone)
        hw = []
        if self._instance_type:
            hw.append(self._instance_type)
        if self._accelerators:
            name, cnt = next(iter(self._accelerators.items()))
            hw.append(f'{name}' + (f':{cnt}' if cnt != 1 else ''))
            spec = self.slice_spec
            if spec is not None and spec.is_pod_slice:
                hw.append(f'[{spec.num_hosts} hosts, {spec.topology_str}]')
        if self._cpus:
            hw.append(f'cpus={self._cpus}')
        if self._memory:
            hw.append(f'mem={self._memory}')
        if self._use_spot:
            hw.append('[spot]')
        loc = '/'.join(parts) if parts else '-'
        return f'Resources({loc}, {", ".join(hw) or "default"})'
