"""Python SDK: thin HTTP client over the API server.

Reference: sky/client/sdk.py (3405 LoC) — every call POSTs to the
server and returns a `request_id` future resolved with `get()` /
`stream_and_get()`. A local API server is auto-started on first use
(`sky api start` behavior).
"""
from __future__ import annotations

import json
import os
import sys
import time
import typing
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib


def api_server_url() -> str:
    env = os.environ.get(constants.API_SERVER_URL_ENV_VAR)
    if env:
        return env.rstrip('/')
    from skypilot_tpu import sky_config
    cfg = sky_config.get_nested(('api_server', 'endpoint'))
    if cfg:
        return str(cfg).rstrip('/')
    return f'http://127.0.0.1:{constants.API_SERVER_PORT}'


def _headers() -> Dict[str, str]:
    from skypilot_tpu.server import versions
    headers = {'X-Skypilot-User': common_utils.get_user_name(),
               versions.HEADER: str(versions.API_VERSION)}
    token = os.environ.get('SKYPILOT_API_TOKEN')
    if not token:
        from skypilot_tpu import sky_config
        token = sky_config.get_nested(('api_server', 'auth_token'))
    if not token:
        # OIDC login fallback (client/oauth.py): cached, auto-refreshed.
        from skypilot_tpu.client import oauth
        token = oauth.get_access_token()
    if token:
        headers['Authorization'] = f'Bearer {token}'
    return headers


def api_info(server_url: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Health + version handshake (reference: sky/server/versions.py).

    Raises ApiVersionMismatchError when the server is older than this
    client can speak to; returns None when unreachable."""
    from skypilot_tpu.server import versions
    url = (server_url or api_server_url()) + '/api/health'
    try:
        resp = requests.get(url, timeout=5, headers=_headers())
        resp.raise_for_status()
        info = resp.json()
    except requests.RequestException:
        return None
    _negotiated, err = versions.check_compatibility(
        info.get('api_version'), remote_side='API server')
    if err:
        raise exceptions.ApiVersionMismatchError(err)
    return info


def api_start(host: str = '127.0.0.1',
              port: Optional[int] = None,
              foreground: bool = False) -> str:
    """Start a local API server if not already running."""
    port = port or constants.API_SERVER_PORT
    url = f'http://{host}:{port}'
    if api_info(url) is not None:
        return url
    if foreground:
        from skypilot_tpu.server import server
        server.run(host, port)
        return url
    log_path = os.path.join(constants.api_server_dir(), 'server.log')
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = f'{repo_root}:{env.get("PYTHONPATH", "")}'
    pid = subprocess_utils.launch_daemon(
        [sys.executable, '-m', 'skypilot_tpu.server.server',
         '--host', host, '--port', str(port)],
        log_path=log_path, env=env)
    deadline = time.time() + 30
    while time.time() < deadline:
        if api_info(url) is not None:
            _write_server_pid(pid)
            return url
        time.sleep(0.5)
    raise exceptions.ApiServerConnectionError(url)


def _server_pid_path() -> str:
    return os.path.join(constants.api_server_dir(), 'server.pid')


def _write_server_pid(pid: int) -> None:
    os.makedirs(constants.api_server_dir(), exist_ok=True)
    with open(_server_pid_path(), 'w', encoding='utf-8') as f:
        f.write(str(pid))


def api_stop() -> bool:
    try:
        with open(_server_pid_path(), 'r', encoding='utf-8') as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return False
    subprocess_utils.kill_process_tree(pid)
    try:
        os.remove(_server_pid_path())
    except OSError:
        pass
    return True


def _ensure_server() -> str:
    url = api_server_url()
    # Probe more than once: a single dropped connection (flaky network,
    # chaos proxy) must not be mistaken for a dead server — that would
    # try to bind a fresh local server on the same port.
    for attempt in range(5):
        if api_info(url) is not None:
            return url
        time.sleep(0.2 * (attempt + 1))
    if url.startswith(('http://127.0.0.1', 'http://localhost')):
        port = int(url.rsplit(':', 1)[1])
        return api_start(port=port)
    raise exceptions.ApiServerConnectionError(url)


def _post(path: str, payload: Dict[str, Any], retries: int = 4) -> str:
    """Schedule a request; retries ride out flaky networks safely.

    Each attempt carries the same client-generated request id, so a
    retry after a lost response re-joins the already-scheduled request
    instead of double-running it (chaos-proxy tested)."""
    import uuid as _uuid
    url = _ensure_server()
    headers = _headers()
    headers['X-Skypilot-Request-ID'] = _uuid.uuid4().hex[:16]
    for attempt in range(retries + 1):
        try:
            resp = requests.post(f'{url}{path}', json=payload,
                                 headers=headers, timeout=30)
            if resp.status_code in (401, 403):
                raise exceptions.PermissionDeniedError(
                    resp.json().get('error', 'permission denied'))
            resp.raise_for_status()
            return resp.json()['request_id']
        except (requests.ConnectionError, requests.Timeout,
                requests.exceptions.ChunkedEncodingError, ValueError):
            if attempt == retries:
                raise
            time.sleep(min(2.0, 0.2 * 2**attempt))
    raise AssertionError('unreachable')  # pragma: no cover


# ---------------------------------------------------------------------------
# Request futures
# ---------------------------------------------------------------------------
def get(request_id: str, timeout: Optional[float] = None) -> Any:
    """Block until the request finishes; return its value or raise.

    Polling GETs are idempotent, so transient connection failures are
    retried (bounded) instead of surfacing to the caller."""
    url = api_server_url()
    deadline = time.time() + timeout if timeout else None
    transient_failures = 0
    while True:
        try:
            resp = requests.get(
                f'{url}/api/get',
                params={'request_id': request_id, 'timeout': 10},
                headers=_headers(), timeout=40)
            if resp.status_code == 404:
                raise exceptions.RequestNotFoundError(request_id)
            resp.raise_for_status()
            body = resp.json()  # truncated body (reset) raises too
            transient_failures = 0
        except (requests.ConnectionError, requests.Timeout,
                requests.exceptions.ChunkedEncodingError, ValueError):
            transient_failures += 1
            if transient_failures > 8:
                raise
            time.sleep(min(2.0, 0.2 * 2**transient_failures))
            continue
        status = body['status']
        if status == 'SUCCEEDED':
            return body.get('return_value')
        if status == 'FAILED':
            raise exceptions.deserialize_exception(body.get('error') or {})
        if status == 'CANCELLED':
            raise exceptions.RequestCancelled(request_id)
        if deadline and time.time() > deadline:
            raise TimeoutError(f'request {request_id} still {status}')


def stream_and_get(request_id: str, output=None) -> Any:
    """Stream the request's log, then return its value (reference:
    sdk.stream_and_get)."""
    url = api_server_url()
    out = output or sys.stderr
    try:
        with requests.get(f'{url}/api/stream',
                          params={'request_id': request_id, 'follow': '1'},
                          headers=_headers(), stream=True,
                          timeout=(30, None)) as resp:
            resp.raise_for_status()
            for line in resp.iter_lines(decode_unicode=True):
                print(line, file=out, flush=True)
    except KeyboardInterrupt:
        print(f'\nDetached from request {request_id}; '
              f'`stpu api logs {request_id}` to re-attach.', file=out)
        raise
    return get(request_id)


def api_cancel(request_id: str) -> bool:
    url = api_server_url()
    resp = requests.post(f'{url}/api/cancel',
                         json={'request_id': request_id},
                         headers=_headers(), timeout=30)
    resp.raise_for_status()
    return resp.json().get('cancelled', False)


def api_status(limit: int = 100) -> List[Dict[str, Any]]:
    url = _ensure_server()
    resp = requests.get(f'{url}/api/status', params={'limit': limit},
                        headers=_headers(), timeout=30)
    resp.raise_for_status()
    return resp.json()['requests']


def api_metrics() -> str:
    """One Prometheus scrape of the API server's /api/metrics
    (orchestration gauges, per-route request histograms, process
    RSS). Returns the raw text exposition."""
    url = _ensure_server()
    resp = requests.get(f'{url}/api/metrics', headers=_headers(),
                        timeout=30)
    resp.raise_for_status()
    return resp.text


# ---------------------------------------------------------------------------
# Verbs (all return request_id)
# ---------------------------------------------------------------------------
def launch(task: 'task_lib.Task', cluster_name: Optional[str] = None,
           *, dryrun: bool = False, detach_run: bool = True,
           idle_minutes_to_autostop: Optional[int] = None,
           down: bool = False, retry_until_up: bool = False,
           no_setup: bool = False,
           optimize_target: str = 'cost',
           env_overrides: Optional[Dict[str, str]] = None) -> str:
    return _post('/launch', {
        'task_config': task.to_yaml_config(),
        'cluster_name': cluster_name,
        'dryrun': dryrun,
        'detach_run': detach_run,
        'idle_minutes_to_autostop': idle_minutes_to_autostop,
        'optimize_target': optimize_target,
        'down': down,
        'retry_until_up': retry_until_up,
        'no_setup': no_setup,
        'env_overrides': env_overrides,
    })


def exec(task: 'task_lib.Task', cluster_name: str,  # pylint: disable=redefined-builtin
         *, dryrun: bool = False, detach_run: bool = True,
         env_overrides: Optional[Dict[str, str]] = None) -> str:
    return _post('/exec', {
        'task_config': task.to_yaml_config(),
        'cluster_name': cluster_name,
        'dryrun': dryrun,
        'detach_run': detach_run,
        'env_overrides': env_overrides,
    })


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> str:
    return _post('/status', {'cluster_names': cluster_names,
                             'refresh': refresh})


def start(cluster_name: str) -> str:
    return _post('/start', {'cluster_name': cluster_name})


def stop(cluster_name: str) -> str:
    return _post('/stop', {'cluster_name': cluster_name})


def down(cluster_name: str, purge: bool = False) -> str:
    return _post('/down', {'cluster_name': cluster_name, 'purge': purge})


def autostop(cluster_name: str, idle_minutes: int,
             down_on_idle: bool = False) -> str:
    return _post('/autostop', {'cluster_name': cluster_name,
                               'idle_minutes': idle_minutes,
                               'down_on_idle': down_on_idle})


def queue(cluster_name: str, all_jobs: bool = False) -> str:
    return _post('/queue', {'cluster_name': cluster_name,
                            'all_jobs': all_jobs})


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> str:
    return _post('/cancel', {'cluster_name': cluster_name,
                             'job_ids': job_ids, 'all_jobs': all_jobs})


def cost_report() -> str:
    return _post('/cost_report', {})


def check() -> str:
    return _post('/check', {})


def static_check(paths: Optional[List[str]] = None,
                 select: Optional[str] = None,
                 include_baselined: bool = False) -> List[dict]:
    """Run the `stpu check` static-analysis suite locally (no server
    round-trip) and return findings as dicts: {rule, path, line, col,
    message}. Baselined findings are dropped unless asked for."""
    from skypilot_tpu import analysis
    from skypilot_tpu.analysis import core as analysis_core
    rules = analysis.resolve_select(select)
    findings = analysis.run_paths(paths or [analysis_core._PKG_DIR],
                                  rules)
    if not include_baselined:
        baseline = analysis_core.Baseline.load(
            analysis_core.DEFAULT_BASELINE)
        findings, _ = baseline.split(findings)
    return [f.to_dict() for f in findings]


def list_accelerators(name_filter: Optional[str] = None,
                      region_filter: Optional[str] = None) -> str:
    return _post('/accelerators', {'name_filter': name_filter,
                                   'region_filter': region_filter})


def storage_ls() -> str:
    return _post('/storage/ls', {})


def storage_delete(name: str) -> str:
    return _post('/storage/delete', {'name': name})


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True, tail: int = 0, output=None) -> None:
    """Stream job logs through the server proxy."""
    url = _ensure_server()
    out = output or sys.stdout
    params = {'cluster': cluster_name, 'follow': '1' if follow else '0'}
    if job_id is not None:
        params['job_id'] = str(job_id)
    if tail:
        params['tail'] = str(tail)
    with requests.get(f'{url}/logs', params=params, headers=_headers(),
                      stream=True, timeout=(30, None)) as resp:
        if resp.status_code == 404:
            raise exceptions.ClusterDoesNotExist(cluster_name)
        resp.raise_for_status()
        for line in resp.iter_lines(decode_unicode=True):
            print(line, file=out, flush=True)


# ---------------------------------------------------------------------------
# Managed jobs
# ---------------------------------------------------------------------------
def jobs_launch(task, name: Optional[str] = None,
                pool: Optional[str] = None) -> str:
    """`task` may be a single Task or a LIST of Tasks (a pipeline:
    stages run sequentially, one cluster each)."""
    if isinstance(task, (list, tuple)):
        config = [t.to_yaml_config() for t in task]
    else:
        config = task.to_yaml_config()
    return _post('/jobs/launch', {
        'task_config': config,
        'name': name,
        'user': common_utils.get_user_name(),
        'pool': pool,
    })


def jobs_queue(refresh: bool = False, skip_finished: bool = False) -> str:
    return _post('/jobs/queue', {'refresh': refresh,
                                 'skip_finished': skip_finished})


def jobs_cancel(job_ids: Optional[List[int]] = None,
                all_jobs: bool = False) -> str:
    return _post('/jobs/cancel', {'job_ids': job_ids, 'all_jobs': all_jobs})


def jobs_logs(job_id: int, follow: bool = True, output=None) -> None:
    url = _ensure_server()
    out = output or sys.stdout
    with requests.get(f'{url}/jobs/logs',
                      params={'job_id': str(job_id),
                              'follow': '1' if follow else '0'},
                      headers=_headers(), stream=True,
                      timeout=(30, None)) as resp:
        if resp.status_code == 404:
            raise exceptions.JobNotFoundError(f'managed job {job_id}')
        resp.raise_for_status()
        for line in resp.iter_lines(decode_unicode=True):
            print(line, file=out, flush=True)


# ---------------------------------------------------------------------------
# Serve
# ---------------------------------------------------------------------------
def serve_up(task: 'task_lib.Task', service_name: str) -> str:
    return _post('/serve/up', {
        'task_config': task.to_yaml_config(),
        'service_name': service_name,
        'user': common_utils.get_user_name(),
    })


def serve_update(task: 'task_lib.Task', service_name: str) -> str:
    return _post('/serve/update', {
        'task_config': task.to_yaml_config(),
        'service_name': service_name,
    })


def serve_status(service_names: Optional[List[str]] = None) -> str:
    return _post('/serve/status', {'service_names': service_names})


def serve_down(service_name: str, purge: bool = False) -> str:
    return _post('/serve/down', {'service_name': service_name,
                                 'purge': purge})


# ---------------------------------------------------------------------------
# Batch
# ---------------------------------------------------------------------------
def batch_launch(task: 'task_lib.Task', name: str, input_path: str,
                 output_dir: str, num_workers: int = 2,
                 num_shards: Optional[int] = None) -> str:
    return _post('/batch/launch', {
        'task_config': task.to_yaml_config(),
        'name': name,
        'input_path': input_path,
        'output_dir': output_dir,
        'num_workers': num_workers,
        'num_shards': num_shards,
        'user': common_utils.get_user_name(),
    })


def batch_ls() -> str:
    return _post('/batch/ls', {})


def batch_cancel(name: str) -> str:
    return _post('/batch/cancel', {'name': name})


# ---------------------------------------------------------------------------
# Managed-job pools
# ---------------------------------------------------------------------------
def jobs_pool_apply(task: 'task_lib.Task', pool_name: str,
                    num_workers: int = 1) -> str:
    return _post('/jobs/pool/apply', {
        'task_config': task.to_yaml_config(),
        'pool_name': pool_name,
        'num_workers': num_workers,
    })


def jobs_pool_ls() -> str:
    return _post('/jobs/pool/ls', {})


def jobs_pool_down(pool_name: str) -> str:
    return _post('/jobs/pool/down', {'pool_name': pool_name})


def jobs_pool_status(pool_name: str) -> str:
    return _post('/jobs/pool/status', {'pool_name': pool_name})


# ---------------------------------------------------------------------------
# Users / RBAC / service-account tokens (reference: sky/client/
# service_account_auth.py + `sky api` auth commands). These routes
# return JSON directly (no request future).
# ---------------------------------------------------------------------------
def _direct(method: str, path: str,
            payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    url = _ensure_server()
    if method == 'GET':
        resp = requests.get(f'{url}{path}', headers=_headers(), timeout=30)
    else:
        resp = requests.post(f'{url}{path}', json=payload or {},
                             headers=_headers(), timeout=30)
    if resp.status_code in (401, 403):
        raise exceptions.PermissionDeniedError(
            resp.json().get('error', 'permission denied'))
    resp.raise_for_status()
    return resp.json()


def users_ls() -> List[Dict[str, Any]]:
    return _direct('GET', '/users')['users']


def users_set_role(user: str, role: str) -> None:
    _direct('POST', '/users/role', {'user': user, 'role': role})


def token_issue(user: str, role: str = 'user') -> Dict[str, str]:
    """Mint a service-account token (admin only). Shown once."""
    return _direct('POST', '/users/tokens', {'user': user, 'role': role})


def token_ls() -> List[Dict[str, Any]]:
    return _direct('GET', '/users/tokens')['tokens']


def token_revoke(token_id: str) -> bool:
    return _direct('POST', '/users/tokens/revoke',
                   {'token_id': token_id})['revoked']


# -- job groups --------------------------------------------------------------
def jobs_group_launch(tasks: List['task_lib.Task'], group_name: str,
                      strategy: Optional[str] = None) -> str:
    """Co-scheduled managed jobs; each task's env gets every peer's
    address (reference: sky/jobs/job_group_networking.py)."""
    return _post('/jobs/group/launch', {
        'group_name': group_name,
        'task_configs': [t.to_yaml_config() for t in tasks],
        'strategy': strategy,
    })


def jobs_group_status(group_name: str) -> str:
    return _post('/jobs/group/status', {'group_name': group_name})


def jobs_group_cancel(group_name: str) -> str:
    return _post('/jobs/group/cancel', {'group_name': group_name})


def serve_logs(service_name: str, follow: bool = True,
               output=None) -> None:
    """Stream a service's controller log."""
    url = _ensure_server()
    out = output or sys.stderr
    with requests.get(f'{url}/serve/logs',
                      params={'service': service_name,
                              'follow': '1' if follow else '0'},
                      headers=_headers(), stream=True,
                      timeout=(30, None)) as resp:
        if resp.status_code == 404:
            raise exceptions.ServiceNotFoundError(service_name)
        resp.raise_for_status()
        for line in resp.iter_lines(decode_unicode=True):
            print(line, file=out, flush=True)
