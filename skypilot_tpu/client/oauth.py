"""OIDC login for the CLI/SDK: authorization-code flow with PKCE.

Reference: sky/client/oauth.py — browser login against the operator's
IdP; the resulting JWT rides every API request as a Bearer token and
the server verifies it offline (users/oidc.py). Tokens are cached at
~/.sky-tpu/oauth_token.json and refreshed with the refresh token.

Config:
  oauth:
    issuer: https://idp.example.com
    client_id: stpu-cli
"""
from __future__ import annotations

import base64
import hashlib
import http.server
import json
import os
import secrets
import threading
import time
import urllib.parse
from typing import Any, Dict, Optional

import requests

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu import sky_config


def _token_path() -> str:
    return os.path.join(constants.sky_home(), 'oauth_token.json')


def _save_tokens(tokens: Dict[str, Any]) -> None:
    path = _token_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, 'w', encoding='utf-8') as f:
        json.dump(tokens, f)


def _load_tokens() -> Optional[Dict[str, Any]]:
    try:
        with open(_token_path(), 'r', encoding='utf-8') as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def logout() -> bool:
    try:
        os.remove(_token_path())
        return True
    except OSError:
        return False


def _discover(issuer: str) -> Dict[str, Any]:
    url = issuer.rstrip('/') + '/.well-known/openid-configuration'
    resp = requests.get(url, timeout=10)
    resp.raise_for_status()
    return resp.json()


class _CallbackHandler(http.server.BaseHTTPRequestHandler):
    code: Optional[str] = None
    state_expected: str = ''
    error: Optional[str] = None

    def do_GET(self):  # noqa: N802
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path != '/callback':
            # Browsers also fetch /favicon.ico etc.; those must not
            # count as a state mismatch against a successful login.
            self.send_response(404)
            self.end_headers()
            return
        query = urllib.parse.parse_qs(parsed.query)
        cls = type(self)
        if query.get('state', [''])[0] != cls.state_expected:
            cls.error = 'state mismatch'
        elif 'error' in query:
            cls.error = query['error'][0]
        else:
            cls.code = query.get('code', [None])[0]
        self.send_response(200)
        self.send_header('Content-Type', 'text/html')
        self.end_headers()
        self.wfile.write(b'<html><body>Login complete; you can close '
                         b'this tab and return to the terminal.'
                         b'</body></html>')

    def log_message(self, *args):  # silence
        del args


def login(issuer: Optional[str] = None,
          client_id: Optional[str] = None,
          open_browser: bool = True,
          timeout: float = 300.0) -> Dict[str, Any]:
    """Run the PKCE authorization-code flow; cache and return tokens."""
    issuer = issuer or sky_config.get_nested(('oauth', 'issuer'))
    client_id = client_id or sky_config.get_nested(('oauth', 'client_id'))
    if not issuer or not client_id:
        raise exceptions.SkyError(
            'OAuth login needs oauth.issuer and oauth.client_id in '
            'config (or pass --issuer/--client-id).')
    meta = _discover(issuer)

    verifier = secrets.token_urlsafe(48)
    challenge = base64.urlsafe_b64encode(
        hashlib.sha256(verifier.encode()).digest()).decode().rstrip('=')
    state = secrets.token_urlsafe(16)

    _CallbackHandler.code = None
    _CallbackHandler.error = None
    _CallbackHandler.state_expected = state
    server = http.server.HTTPServer(('127.0.0.1', 0), _CallbackHandler)
    port = server.server_address[1]
    redirect_uri = f'http://127.0.0.1:{port}/callback'
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    params = {
        'response_type': 'code',
        'client_id': client_id,
        'redirect_uri': redirect_uri,
        'scope': 'openid email profile offline_access',
        'state': state,
        'code_challenge': challenge,
        'code_challenge_method': 'S256',
    }
    authorize_url = (meta['authorization_endpoint'] + '?' +
                     urllib.parse.urlencode(params))
    print(f'Open this URL to log in:\n  {authorize_url}')
    if open_browser:
        import webbrowser
        webbrowser.open(authorize_url)

    deadline = time.time() + timeout
    try:
        while _CallbackHandler.code is None and \
                _CallbackHandler.error is None:
            if time.time() > deadline:
                raise exceptions.SkyError('OAuth login timed out.')
            time.sleep(0.2)
    finally:
        server.shutdown()
        thread.join(timeout=5)
    if _CallbackHandler.error:
        raise exceptions.SkyError(
            f'OAuth login failed: {_CallbackHandler.error}')

    resp = requests.post(meta['token_endpoint'], data={
        'grant_type': 'authorization_code',
        'code': _CallbackHandler.code,
        'redirect_uri': redirect_uri,
        'client_id': client_id,
        'code_verifier': verifier,
    }, timeout=30)
    resp.raise_for_status()
    tokens = resp.json()
    tokens['issuer'] = issuer
    tokens['client_id'] = client_id
    tokens['expires_at'] = time.time() + float(
        tokens.get('expires_in', 3600))
    _save_tokens(tokens)
    return tokens


def _refresh(tokens: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    refresh_token = tokens.get('refresh_token')
    if not refresh_token:
        return None
    try:
        meta = _discover(tokens['issuer'])
        resp = requests.post(meta['token_endpoint'], data={
            'grant_type': 'refresh_token',
            'refresh_token': refresh_token,
            'client_id': tokens.get('client_id', ''),
        }, timeout=30)
        resp.raise_for_status()
        new = resp.json()
    except (requests.RequestException, KeyError, ValueError):
        return None
    tokens = {**tokens, **new}
    if 'id_token' not in new:
        # Refresh grants may return only an access token; keeping the
        # old (expired) id_token would make get_access_token serve a
        # JWT the server rejects while the client thinks it's fresh.
        tokens.pop('id_token', None)
    tokens['expires_at'] = time.time() + float(new.get('expires_in', 3600))
    _save_tokens(tokens)
    return tokens


# Failed-refresh backoff: without it, an expired token + unreachable
# IdP would add discovery+refresh timeouts to EVERY SDK/CLI call.
_refresh_failed_at = 0.0
_REFRESH_RETRY_INTERVAL = 60.0


def get_access_token() -> Optional[str]:
    """The cached (auto-refreshed) access token, or None if not
    logged in. Used by sdk._headers as the Bearer fallback."""
    global _refresh_failed_at
    tokens = _load_tokens()
    if tokens is None:
        return None
    if time.time() >= float(tokens.get('expires_at', 0)) - 30:
        if time.time() - _refresh_failed_at < _REFRESH_RETRY_INTERVAL:
            return None
        tokens = _refresh(tokens)
        if tokens is None:
            _refresh_failed_at = time.time()
            return None
        _refresh_failed_at = 0.0
    # id_token carries the identity claims the server verifies;
    # fall back to access_token for IdPs that make it a JWT too.
    return tokens.get('id_token') or tokens.get('access_token')
