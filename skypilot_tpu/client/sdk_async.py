"""Async SDK: the sdk.py verbs as coroutines over one aiohttp session.

Reference: sky/client/sdk_async.py — same surface as the sync SDK,
returning request ids awaitable via `get`/`stream_and_get`. Shares the
sync module's endpoint resolution, auth headers, and version handshake
so the two clients cannot drift; transport is aiohttp so callers can
fan out many control-plane calls concurrently (e.g. launching N
clusters from one coroutine).

Usage:
    async with AsyncClient() as client:
        rid = await client.launch(task, cluster_name='c1')
        result = await client.get(rid)
"""
from __future__ import annotations

import asyncio
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

import aiohttp

from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk as sync_sdk


class AsyncClient:
    """One aiohttp session over the configured API server."""

    def __init__(self, server_url: Optional[str] = None) -> None:
        self._url = (server_url or sync_sdk.api_server_url()).rstrip('/')
        self._session: Optional[aiohttp.ClientSession] = None

    async def __aenter__(self) -> 'AsyncClient':
        self._session = aiohttp.ClientSession()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    @property
    def session(self) -> aiohttp.ClientSession:
        assert self._session is not None, \
            'use `async with AsyncClient() as client:`'
        return self._session

    # -- plumbing -----------------------------------------------------------
    @staticmethod
    async def _headers() -> Dict[str, str]:
        # sync_sdk._headers() reads config YAML from disk and may do
        # network I/O (OAuth token refresh) — off the event loop.
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(
            None, sync_sdk._headers)  # pylint: disable=protected-access

    async def _post(self, path: str, payload: Dict[str, Any],
                    retries: int = 4) -> str:
        headers = await self._headers()
        # Same idempotency contract as the sync SDK: one client id per
        # logical request, so retries re-join instead of double-run.
        headers['X-Skypilot-Request-ID'] = uuid.uuid4().hex[:16]
        for attempt in range(retries + 1):
            try:
                async with self.session.post(
                        f'{self._url}{path}', json=payload,
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(total=30)) as resp:
                    if resp.status in (401, 403):
                        body = await resp.json()
                        raise exceptions.PermissionDeniedError(
                            body.get('error', 'permission denied'))
                    resp.raise_for_status()
                    body = await resp.json()
                    return body['request_id']
            except (aiohttp.ClientConnectionError,
                    aiohttp.ClientPayloadError,
                    asyncio.TimeoutError, ValueError) as e:
                # ClientPayloadError/ValueError: reset-mid-body or a
                # truncated JSON — the same transient class the sync
                # SDK retries (chaos-proxy contract).
                if attempt == retries:
                    raise exceptions.ApiServerConnectionError(
                        f'{self._url}: {e}') from e
                await asyncio.sleep(min(2.0, 0.2 * 2**attempt))
        raise AssertionError('unreachable')  # pragma: no cover

    async def get(self, request_id: str,
                  timeout: Optional[float] = None) -> Any:
        """Await a request's result (long-poll loop, like sdk.get)."""
        deadline = time.time() + timeout if timeout else None
        transient = 0
        headers = await self._headers()
        while True:
            try:
                async with self.session.get(
                        f'{self._url}/api/get',
                        params={'request_id': request_id, 'timeout': 10},
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(total=40)) as resp:
                    if resp.status == 404:
                        raise exceptions.RequestNotFoundError(request_id)
                    resp.raise_for_status()
                    body = await resp.json()
                transient = 0
            except (aiohttp.ClientConnectionError,
                    aiohttp.ClientPayloadError,
                    asyncio.TimeoutError, ValueError):
                transient += 1
                if transient > 8:
                    raise
                await asyncio.sleep(min(2.0, 0.2 * 2**transient))
                continue
            status = body['status']
            if status == 'SUCCEEDED':
                return body.get('return_value')
            if status == 'FAILED':
                raise exceptions.deserialize_exception(
                    body.get('error') or {})
            if status == 'CANCELLED':
                raise exceptions.RequestCancelled(request_id)
            if deadline and time.time() > deadline:
                raise TimeoutError(f'request {request_id} still {status}')

    async def stream_and_get(self, request_id: str, output=None) -> Any:
        """Stream the request's log lines, then return its value."""
        out = output or sys.stderr
        headers = await self._headers()
        async with self.session.get(
                f'{self._url}/api/stream',
                params={'request_id': request_id, 'follow': '1'},
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=None,
                                              sock_connect=30)) as resp:
            resp.raise_for_status()
            async for raw in resp.content:
                print(raw.decode(errors='replace').rstrip('\n'),
                      file=out, flush=True)
        return await self.get(request_id)

    async def api_cancel(self, request_id: str) -> bool:
        headers = await self._headers()
        async with self.session.post(
                f'{self._url}/api/cancel',
                json={'request_id': request_id}, headers=headers,
                timeout=aiohttp.ClientTimeout(total=30)) as resp:
            resp.raise_for_status()
            return (await resp.json()).get('cancelled', False)

    # -- verbs (same payloads as sdk.py) ------------------------------------
    async def launch(self, task, cluster_name: Optional[str] = None, *,
                     dryrun: bool = False, detach_run: bool = True,
                     idle_minutes_to_autostop: Optional[int] = None,
                     down: bool = False, retry_until_up: bool = False,
                     no_setup: bool = False, optimize_target: str = 'cost',
                     env_overrides: Optional[Dict[str, str]] = None) -> str:
        return await self._post('/launch', {
            'task_config': task.to_yaml_config(),
            'cluster_name': cluster_name,
            'dryrun': dryrun,
            'detach_run': detach_run,
            'idle_minutes_to_autostop': idle_minutes_to_autostop,
            'optimize_target': optimize_target,
            'down': down,
            'retry_until_up': retry_until_up,
            'no_setup': no_setup,
            'env_overrides': env_overrides,
        })

    async def exec(self, task, cluster_name: str, *,  # pylint: disable=redefined-builtin
                   dryrun: bool = False, detach_run: bool = True,
                   env_overrides: Optional[Dict[str, str]] = None) -> str:
        return await self._post('/exec', {
            'task_config': task.to_yaml_config(),
            'cluster_name': cluster_name,
            'dryrun': dryrun,
            'detach_run': detach_run,
            'env_overrides': env_overrides,
        })

    async def status(self, cluster_names: Optional[List[str]] = None,
                     refresh: bool = False) -> str:
        return await self._post('/status',
                                {'cluster_names': cluster_names,
                                 'refresh': refresh})

    async def start(self, cluster_name: str) -> str:
        return await self._post('/start', {'cluster_name': cluster_name})

    async def stop(self, cluster_name: str) -> str:
        return await self._post('/stop', {'cluster_name': cluster_name})

    async def down(self, cluster_name: str, purge: bool = False) -> str:
        return await self._post('/down', {'cluster_name': cluster_name,
                                          'purge': purge})

    async def autostop(self, cluster_name: str, idle_minutes: int,
                       down_on_idle: bool = False) -> str:
        return await self._post('/autostop',
                                {'cluster_name': cluster_name,
                                 'idle_minutes': idle_minutes,
                                 'down_on_idle': down_on_idle})

    async def queue(self, cluster_name: str, all_jobs: bool = False) -> str:
        return await self._post('/queue', {'cluster_name': cluster_name,
                                           'all_jobs': all_jobs})

    async def cancel(self, cluster_name: str,
                     job_ids: Optional[List[int]] = None,
                     all_jobs: bool = False) -> str:
        return await self._post('/cancel', {'cluster_name': cluster_name,
                                            'job_ids': job_ids,
                                            'all_jobs': all_jobs})

    async def cost_report(self) -> str:
        return await self._post('/cost_report', {})

    async def check(self) -> str:
        return await self._post('/check', {})

    async def list_accelerators(
            self, name_filter: Optional[str] = None,
            region_filter: Optional[str] = None) -> str:
        return await self._post('/accelerators',
                                {'name_filter': name_filter,
                                 'region_filter': region_filter})

    async def storage_ls(self) -> str:
        return await self._post('/storage/ls', {})

    async def storage_delete(self, name: str) -> str:
        return await self._post('/storage/delete', {'name': name})

    async def jobs_queue(self, refresh: bool = False,
                         skip_finished: bool = False) -> str:
        return await self._post('/jobs/queue',
                                {'refresh': refresh,
                                 'skip_finished': skip_finished})

    async def serve_status(
            self, service_names: Optional[List[str]] = None) -> str:
        return await self._post('/serve/status',
                                {'service_names': service_names})
