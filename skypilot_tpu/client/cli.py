"""CLI: `stpu` — thin wrappers that build Tasks, call the SDK, and
poll request ids.

Reference: sky/client/cli/command.py (8468 LoC, 105 commands). Core
command set here; jobs/serve groups register from their modules.
"""
from __future__ import annotations

import datetime
import os
import sys
from typing import Any, Dict, List, Optional

import click

from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk
from skypilot_tpu.utils import common_utils


def _err(message: str) -> None:
    click.secho(f'Error: {message}', fg='red', err=True)
    sys.exit(1)


def _parse_env(env: List[str]) -> Dict[str, str]:
    out = {}
    for item in env:
        if '=' in item:
            k, v = item.split('=', 1)
            out[k] = v
        else:
            v = os.environ.get(item)
            if v is None:
                _err(f'--env {item}: not set in the caller environment')
            out[item] = v
    return out


def _parse_env_file(path: str) -> Dict[str, str]:
    """dotenv-style KEY=VAL lines; '#' comments and blanks skipped."""
    out: Dict[str, str] = {}
    try:
        with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith('#') or '=' not in line:
                    continue
                k, v = line.split('=', 1)
                out[k.strip()] = v.strip().strip('"').strip("'")
    except OSError as e:
        _err(f'--env-file {path}: {e}')
    return out


def _merged_env(env, env_file) -> Dict[str, str]:
    """--env-file entries with --env flags overriding on conflict."""
    out: Dict[str, str] = {}
    if env_file:
        out.update(_parse_env_file(env_file))
    out.update(_parse_env(list(env or [])))
    return out


def _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                num_nodes, use_spot, env, cmd=None, env_file=None):
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    env_overrides = _merged_env(env, env_file)
    if entrypoint and entrypoint.endswith(('.yaml', '.yml')):
        config = common_utils.read_yaml(os.path.expanduser(entrypoint))
        task = task_lib.Task.from_yaml_config(config, env_overrides)
    else:
        run_cmd = cmd or entrypoint
        task = task_lib.Task(run=run_cmd, envs=env_overrides)
    if name:
        task.name = name
    if workdir:
        task.workdir = workdir
    if num_nodes:
        task.num_nodes = num_nodes
    overrides: Dict[str, Any] = {}
    if infra:
        overrides['infra'] = infra
    if gpus:
        overrides['accelerators'] = gpus
    if cpus:
        overrides['cpus'] = cpus
    if memory:
        overrides['memory'] = memory
    if use_spot is not None:
        overrides['use_spot'] = use_spot
    if overrides:
        task.set_resources({r.copy(**overrides) for r in task.resources})
    return task


@click.group()
@click.version_option('0.1.0', prog_name='stpu')
def cli() -> None:
    """stpu: TPU-native sky orchestrator."""


# ---------------------------------------------------------------------------
# launch / exec
# ---------------------------------------------------------------------------
_task_options = [
    click.option('--name', '-n', default=None, help='Task name.'),
    click.option('--workdir', default=None,
                 help='Directory synced to ~/sky_workdir.'),
    click.option('--infra', default=None,
                 help='cloud[/region[/zone]], e.g. gcp/us-central2.'),
    click.option('--gpus', '--tpus', 'gpus', default=None,
                 help='Accelerator, e.g. tpu-v5p-128 or A100:8.'),
    click.option('--cpus', default=None),
    click.option('--memory', default=None),
    click.option('--num-nodes', type=int, default=None,
                 help='Number of nodes (TPU: slices).'),
    click.option('--use-spot/--no-use-spot', default=None),
    click.option('--env', multiple=True,
                 help='KEY=VAL or KEY (inherit).'),
    click.option('--env-file', default=None,
                 help='dotenv file; --env flags override its entries.'),
]


def _add_options(options):

    def wrap(f):
        for opt in reversed(options):
            f = opt(f)
        return f

    return wrap


@cli.command()
@click.argument('entrypoint', required=False)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@_add_options(_task_options)
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False,
              help='Autodown after the job finishes / on idle.')
@click.option('--retry-until-up', '-r', is_flag=True, default=False)
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--no-setup', is_flag=True, default=False)
@click.option('--optimize-target', type=click.Choice(['cost', 'time']),
              default='cost', help='Rank candidate hardware by $ or by '
                                   'estimated runtime.')
@click.option('--yes', '-y', is_flag=True, default=False)
def launch(entrypoint, cluster, name, workdir, infra, gpus, cpus, memory,
           num_nodes, use_spot, env, env_file, idle_minutes_to_autostop, down,
           retry_until_up, dryrun, detach_run, no_setup, optimize_target,
           yes) -> None:
    """Launch a task from YAML or a command (provisions a cluster)."""
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, env_file=env_file)
    if not yes and not dryrun:
        r = sorted(str(x) for x in task.resources)
        target = cluster or 'new cluster'
        click.echo(f'Launching {task.name or "task"} on {target}: {r}')
        click.confirm('Proceed?', default=True, abort=True)
    request_id = sdk.launch(
        task, cluster_name=cluster, dryrun=dryrun,
        detach_run=True,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        retry_until_up=retry_until_up, no_setup=no_setup,
        optimize_target=optimize_target)
    result = sdk.stream_and_get(request_id)
    if result and result.get('job_id') is not None and not detach_run:
        cname = (result.get('handle') or {}).get('cluster_name') or cluster
        sdk.tail_logs(cname, result['job_id'])


@cli.command(name='exec')
@click.argument('cluster')
@click.argument('entrypoint')
@_add_options(_task_options)
@click.option('--detach-run', '-d', is_flag=True, default=False)
def exec_cmd(cluster, entrypoint, name, workdir, infra, gpus, cpus, memory,
             num_nodes, use_spot, env, env_file, detach_run) -> None:
    """Run a task on an existing cluster (no provisioning)."""
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, env_file=env_file)
    request_id = sdk.exec(task, cluster, detach_run=True)
    result = sdk.stream_and_get(request_id)
    if result.get('job_id') is not None and not detach_run:
        sdk.tail_logs(cluster, result['job_id'])


# ---------------------------------------------------------------------------
# status & lifecycle
# ---------------------------------------------------------------------------
@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh', '-r', is_flag=True, default=False)
@click.option('--endpoints', is_flag=True, default=False,
              help='Show head IP and opened-port URLs instead.')
@click.option('--kubernetes', '--k8s', 'kubernetes', is_flag=True,
              default=False,
              help='List ALL framework-managed pods in the current '
                   'kube context instead of this server\'s clusters.')
def status(clusters, refresh, endpoints, kubernetes) -> None:
    """Show clusters (or, with --kubernetes, every managed pod)."""
    if kubernetes:
        from rich.console import Console
        from rich.table import Table
        from skypilot_tpu.provision.kubernetes import instance as k8s_inst
        pods = k8s_inst.list_skypilot_pods()
        table = Table(box=None)
        for col in ('CLUSTER', 'POD', 'RANK', 'PHASE', 'NODE'):
            table.add_column(col)
        for pod in sorted(pods, key=lambda x: (x['cluster'],
                                               int(x['node_rank']))):
            table.add_row(pod['cluster'], pod['name'], pod['node_rank'],
                          pod['phase'], pod['node'])
        Console().print(table)
        return
    request_id = sdk.status(list(clusters) or None, refresh=refresh)
    records = sdk.get(request_id)
    if not records:
        click.echo('No existing clusters.')
        return
    if endpoints:
        for r in records:
            ip = r.get('head_ip')
            # A stopped cluster's handle keeps its last IPs — showing
            # them as live endpoints would point at released addresses.
            if not ip or r['status'] != 'UP':
                click.echo(f'{r["name"]}: (no endpoint — '
                           f'status {r["status"]})')
                continue
            ports = r.get('ports') or []
            if ports:
                for p in ports:
                    if '-' in str(p):
                        click.echo(f'{r["name"]}: {ip} ports {p}')
                    else:
                        click.echo(f'{r["name"]}: http://{ip}:{p}')
            else:
                click.echo(f'{r["name"]}: {ip} (no ports opened)')
        return
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'LAUNCHED', 'RESOURCES', 'STATUS', 'AUTOSTOP'):
        table.add_column(col)
    for r in records:
        launched = datetime.datetime.fromtimestamp(
            r['launched_at']).strftime('%Y-%m-%d %H:%M')
        autostop = (f'{r["autostop"]}m'
                    f'{" (down)" if r["autostop_down"] else ""}'
                    if r['autostop'] is not None and r['autostop'] >= 0
                    else '-')
        table.add_row(r['name'], launched, r['resources_str'] or '-',
                      r['status'], autostop)
    Console().print(table)


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--all', '-a', 'all_clusters', is_flag=True, default=False,
              help='Start every STOPPED cluster.')
@click.option('--yes', '-y', is_flag=True, default=False)
def start(clusters, all_clusters, yes) -> None:
    """Restart stopped cluster(s)."""
    clusters = _resolve_cluster_args(clusters, all_clusters, 'start',
                                     status_filter='STOPPED')
    if all_clusters and not yes:
        click.confirm(f'Start {", ".join(clusters)}?', abort=True)
    for c in clusters:
        sdk.stream_and_get(sdk.start(c))
        click.echo(f'Cluster {c} started.')


def _resolve_cluster_args(clusters, all_clusters: bool, verb: str,
                          status_filter: Optional[str] = None
                          ) -> List[str]:
    if all_clusters:
        records = sdk.get(sdk.status())
        names = [r['name'] for r in records
                 if status_filter is None or r['status'] == status_filter]
        if not names:
            noun = (f'{status_filter} clusters'.lower()
                    if status_filter else 'existing clusters')
            click.echo(f'No {noun}.')
            sys.exit(0)
        return names
    if not clusters:
        raise click.UsageError(f'specify cluster name(s) or --all to '
                               f'{verb} every cluster')
    return list(clusters)


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--all', '-a', 'all_clusters', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(clusters, all_clusters, yes) -> None:
    """Stop cluster(s) (keep disks)."""
    clusters = _resolve_cluster_args(clusters, all_clusters, 'stop')
    if not yes:
        click.confirm(f'Stop {", ".join(clusters)}?', abort=True)
    for c in clusters:
        sdk.stream_and_get(sdk.stop(c))
        click.echo(f'Cluster {c} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--all', '-a', 'all_clusters', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False,
              help='Remove from state even if cloud cleanup fails.')
def down(clusters, all_clusters, yes, purge) -> None:
    """Terminate cluster(s)."""
    clusters = _resolve_cluster_args(clusters, all_clusters, 'terminate')
    if not yes:
        click.confirm(f'Terminate {", ".join(clusters)}?', abort=True)
    for c in clusters:
        sdk.stream_and_get(sdk.down(c, purge=purge))
        click.echo(f'Cluster {c} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, required=True,
              help='-1 cancels autostop.')
@click.option('--down', is_flag=True, default=False)
def autostop(cluster, idle_minutes, down) -> None:
    """Set autostop/autodown on a cluster."""
    sdk.get(sdk.autostop(cluster, idle_minutes, down))
    click.echo(f'Autostop set on {cluster}: {idle_minutes}m '
               f'({"down" if down else "stop"}).')


# ---------------------------------------------------------------------------
# jobs on clusters
# ---------------------------------------------------------------------------
@cli.command()
@click.argument('cluster')
@click.option('--all-jobs', '-a', is_flag=True, default=False)
def queue(cluster, all_jobs) -> None:
    """Show a cluster's job queue."""
    jobs = sdk.get(sdk.queue(cluster, all_jobs))
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('ID', 'NAME', 'USER', 'SUBMITTED', 'STATUS'):
        table.add_column(col)
    for j in jobs:
        ts = datetime.datetime.fromtimestamp(
            j['submitted_at']).strftime('%H:%M:%S')
        table.add_row(str(j['job_id']), j.get('job_name') or '-',
                      j.get('username') or '-', ts, j['status'])
    Console().print(table)


@cli.command()
@click.argument('cluster')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True, default=False)
def cancel(cluster, job_ids, all_jobs) -> None:
    """Cancel job(s) on a cluster."""
    if not job_ids and not all_jobs:
        _err('specify job ids or --all')
    sdk.get(sdk.cancel(cluster, list(job_ids) or None, all_jobs))
    click.echo('Cancelled.')


@cli.command()
@click.argument('cluster')
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True, default=False)
@click.option('--tail', type=int, default=0)
@click.option('--sync-down', is_flag=True, default=False,
              help='Download the log to ~/sky_logs_download/ instead '
                   'of streaming it.')
def logs(cluster, job_id, no_follow, tail, sync_down) -> None:
    """Tail a job's logs (or download them with --sync-down)."""
    try:
        if sync_down:
            dst_dir = os.path.expanduser(
                os.path.join('~/sky_logs_download', cluster))
            os.makedirs(dst_dir, exist_ok=True)
            dst = os.path.join(dst_dir, f'job-{job_id or "latest"}.log')
            with open(dst, 'w', encoding='utf-8') as f:
                sdk.tail_logs(cluster, job_id, follow=False, tail=0,
                              output=f)
            click.echo(f'Log synced to {dst}')
            return
        sdk.tail_logs(cluster, job_id, follow=not no_follow, tail=tail)
    except exceptions.ClusterDoesNotExist as e:
        _err(str(e))


@cli.command()
@click.argument('cluster')
@click.option('--node', type=int, default=0,
              help='Host index to attach to (0 = head).')
def attach(cluster, node) -> None:
    """Interactive shell on a cluster host via the API server's
    websocket PTY bridge (reference: the server-side SSH tunnel —
    no direct network path to the cluster needed)."""
    from skypilot_tpu.server import attach as attach_mod
    token = None
    auth = sdk._headers().get('Authorization', '')  # pylint: disable=protected-access
    if auth.startswith('Bearer '):
        token = auth[len('Bearer '):]
    raise SystemExit(attach_mod.run_client(
        sdk.api_server_url(), cluster, node=node, token=token))


# ---------------------------------------------------------------------------
# info
# ---------------------------------------------------------------------------
@cli.command()
@click.argument('targets', nargs=-1)
@click.option('--format', 'fmt', type=click.Choice(['text', 'json']),
              default='text', help='Static-analysis report format.')
@click.option('--select', default=None, metavar='RULES',
              help='Comma-separated rules to run, e.g. SKY001,SKY003.')
@click.option('--baseline', 'baseline_path', default=None,
              metavar='PATH',
              help='Baseline JSON (default: the committed '
                   'analysis/baseline.json).')
@click.option('--no-baseline', is_flag=True, default=False,
              help='Report baselined findings too.')
@click.option('--write-baseline', is_flag=True, default=False,
              help='Rewrite the baseline file to grandfather every '
                   'current finding (requires --justification).')
@click.option('--justification', default=None,
              help='One-line reason recorded on entries written by '
                   '--write-baseline.')
@click.option('--changed', is_flag=True, default=False,
              help='Analyze only files changed vs --base (fast '
                   'pre-commit iteration; uses `git diff '
                   '--name-only`).')
@click.option('--base', default='HEAD', metavar='REF',
              help='Git ref --changed diffs against (default HEAD: '
                   'uncommitted work).')
@click.option('--migrate-baseline', 'migrate_baseline', is_flag=True,
              default=False,
              help='One-shot: rewrite a v1 (line-keyed) baseline as '
                   'v2 (symbol-keyed), preserving justifications; '
                   'stale rows are dropped.')
def check(targets, fmt, select, baseline_path, no_baseline,
          write_baseline, justification, changed, base,
          migrate_baseline) -> None:
    """Static analysis (`stpu check skypilot_tpu/`) or cloud probe.

    With PATH arguments — or any of --select/--format/--baseline/
    --changed — runs the SKY static-analysis suite (async-safety,
    jit-purity, lock discipline, metric hygiene, exception hygiene,
    pallas-interpret reachability, span discipline, thread
    ownership, donation discipline, fault-point drift; see
    docs/internals.md) and exits
    non-zero on any non-baselined finding. With cloud-name arguments (or none), probes cloud
    credentials and caches enabled clouds (the original behavior).
    """
    static_flags = (fmt != 'text' or select or baseline_path or
                    no_baseline or write_baseline or changed or
                    migrate_baseline)
    path_args = any(os.path.exists(t) or t.endswith('.py') or
                    os.sep in t for t in targets)
    if not static_flags and not path_args:
        enabled = sdk.get(sdk.check())
        if targets:
            for c in targets:
                mark = 'enabled' if c.lower() in enabled else 'disabled'
                click.echo(f'{c.lower()}: {mark}')
            return
        click.echo(f'Enabled clouds: {", ".join(enabled) or "none"}')
        return

    from skypilot_tpu import analysis
    from skypilot_tpu.analysis import core as analysis_core
    paths = list(targets)
    if not paths:
        # Default target: the installed package tree.
        paths = [analysis_core._PKG_DIR]
    if changed:
        paths = _changed_python_files(paths, base)
        if not paths:
            click.echo(f'no changed .py files vs {base}')
            sys.exit(0)
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        _err(f'no such path(s): {", ".join(missing)}')
    try:
        rules = analysis.resolve_select(select)
    except ValueError as e:
        _err(str(e))
    timings: dict = {}
    findings = analysis.run_paths(paths, rules, timings)
    if write_baseline:
        if not justification:
            _err('--write-baseline requires --justification '
                 '(the baseline is for triaged false positives, '
                 'each with a reason)')
        out = baseline_path or analysis_core.DEFAULT_BASELINE
        analysis_core.Baseline.from_findings(
            findings, justification).save(out)
        click.echo(f'Wrote {len(findings)} entr'
                   f'{"y" if len(findings) == 1 else "ies"} to {out}')
        return
    baseline = analysis_core.Baseline.load(
        baseline_path or analysis_core.DEFAULT_BASELINE)
    if migrate_baseline:
        out = baseline_path or analysis_core.DEFAULT_BASELINE
        migrated = baseline.migrated(findings)
        dropped = len(baseline.entries) - len(migrated.entries)
        migrated.save(out)
        click.echo(f'Migrated {out} to v2: {len(migrated.entries)} '
                   f'symbol-keyed entr'
                   f'{"y" if len(migrated.entries) == 1 else "ies"}'
                   f'{f", {dropped} stale dropped" if dropped else ""}')
        return
    if no_baseline:
        new, baselined = list(findings), []
    else:
        new, baselined = baseline.split(findings)
    if fmt == 'json':
        click.echo(analysis.render_json(new, baselined, timings))
    else:
        click.echo(analysis.render_text(new, baselined))
    sys.exit(1 if new else 0)


def _changed_python_files(scope_paths, base: str):
    """`.py` files changed vs git ref `base`, intersected with the
    requested scope — `stpu check --changed` pre-commit mode."""
    import subprocess
    from skypilot_tpu.analysis import core as analysis_core
    try:
        out = subprocess.run(
            ['git', 'diff', '--name-only', base, '--'],
            capture_output=True, text=True, check=True,
            cwd=analysis_core.REPO_ROOT)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, 'stderr', '') or str(e)
        _err(f'--changed: git diff --name-only {base} failed: '
             f'{detail.strip()}')
    scope = [os.path.abspath(p) for p in scope_paths]
    files = []
    for rel in out.stdout.splitlines():
        if not rel.endswith('.py'):
            continue
        path = os.path.join(analysis_core.REPO_ROOT, rel)
        if not os.path.exists(path):
            continue  # deleted in the diff
        abs_path = os.path.abspath(path)
        if any(abs_path == s or abs_path.startswith(s + os.sep)
               for s in scope):
            files.append(path)
    return files


@cli.command(name='gpus')
@click.argument('accelerator', required=False)
@click.option('--region', default=None)
def gpus(accelerator, region) -> None:
    """List TPU/GPU offerings and prices (`stpu gpus tpu-v5p`)."""
    result = sdk.get(sdk.list_accelerators(accelerator, region))
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('ACCELERATOR', 'REGION', '$/hr', '$/hr (spot)', 'HOSTS',
                'TOPOLOGY'):
        table.add_column(col)
    from skypilot_tpu.utils import tpu_utils
    for acc in sorted(result):
        infos = result[acc]
        regions_seen = set()
        for info in infos:
            if info['region'] in regions_seen:
                continue
            regions_seen.add(info['region'])
            hosts = topo = '-'
            if tpu_utils.is_tpu(acc):
                spec = tpu_utils.get_slice_spec(acc)
                hosts, topo = str(spec.num_hosts), spec.topology_str
            table.add_row(acc, info['region'], f"{info['price']:.2f}",
                          f"{info['spot_price']:.2f}", hosts, topo)
    Console().print(table)


@cli.command(name='cost-report')
def cost_report() -> None:
    """Show cost of terminated clusters."""
    rows = sdk.get(sdk.cost_report())
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'RESOURCES', 'DURATION', 'COST ($)'):
        table.add_column(col)
    for r in rows:
        mins = (r['duration'] or 0) / 60
        table.add_row(r['name'], r['resources_str'] or '-',
                      f'{mins:.0f}m', f"{r['cost'] or 0:.2f}")
    Console().print(table)


@cli.command(name='metrics')
@click.option('--url', default=None, metavar='URL',
              help='Scrape this URL instead of the API server '
                   '(e.g. an inference replica: '
                   'http://HOST:PORT/metrics).')
@click.option('--stats', is_flag=True, default=False,
              help='Fetch the JSON /stats snapshot from an inference '
                   'server instead of Prometheus text (requires '
                   '--url or defaults to the replica root of URL).')
def metrics_cmd(url: Optional[str], stats: bool) -> None:
    """One metrics scrape: the API server's /api/metrics by default,
    or any replica's /metrics (--url) / JSON /stats (--stats).
    Prometheus text goes to stdout — pipe into grep/promtool."""
    import json as _json

    import requests as _requests
    if stats:
        if not url:
            _err('--stats needs --url http://HOST:PORT '
                 '(an inference replica)')
            return
        base = url.rstrip('/')
        if base.endswith('/metrics'):
            base = base[:-len('/metrics')]
        if not base.endswith('/stats'):
            base = base + '/stats'
        resp = _requests.get(base, timeout=15)
        resp.raise_for_status()
        click.echo(_json.dumps(resp.json(), indent=2))
        return
    if url:
        resp = _requests.get(url, timeout=15)
        resp.raise_for_status()
        click.echo(resp.text, nl=False)
        return
    click.echo(sdk.api_metrics(), nl=False)


# ---------------------------------------------------------------------------
# storage group
# ---------------------------------------------------------------------------
@cli.group()
def storage() -> None:
    """Manage storage objects."""


@storage.command(name='ls')
def storage_ls() -> None:
    names = sdk.get(sdk.storage_ls())
    for n in names:
        click.echo(n)


@storage.command(name='transfer')
@click.argument('src')
@click.argument('dst')
@click.option('--size-gb', type=float, default=None,
              help='estimated size; large S3->GCS jobs use the '
                   'server-side Storage Transfer Service')
@click.option('--dryrun', is_flag=True, default=False,
              help='print the transfer plan without executing')
def storage_transfer(src, dst, size_gb, dryrun) -> None:
    """Move bucket contents across clouds (gs:// <-> s3://)."""
    from skypilot_tpu import sky_config
    from skypilot_tpu.data import transfer as transfer_lib
    plan = transfer_lib.transfer(
        src, dst, size_gigabytes=size_gb,
        project_id=sky_config.get_nested(('gcp', 'project_id')),
        run=not dryrun)
    click.echo(f'method: {plan["method"]}')
    if 'command' in plan:
        click.echo(plan['command'])


@storage.command(name='delete')
@click.argument('name')
@click.option('--yes', '-y', is_flag=True, default=False)
def storage_delete(name, yes) -> None:
    if not yes:
        click.confirm(f'Delete storage {name}?', abort=True)
    sdk.get(sdk.storage_delete(name))


# ---------------------------------------------------------------------------
# api group
# ---------------------------------------------------------------------------
@cli.group()
def api() -> None:
    """Manage the API server."""


@api.command(name='start')
@click.option('--host', default='127.0.0.1')
@click.option('--port', type=int, default=None)
@click.option('--foreground', is_flag=True, default=False)
def api_start(host, port, foreground) -> None:
    url = sdk.api_start(host=host, port=port, foreground=foreground)
    click.echo(f'API server running at {url}')


@api.command(name='stop')
def api_stop() -> None:
    if sdk.api_stop():
        click.echo('API server stopped.')
    else:
        click.echo('No local API server found.')


@api.command(name='info')
def api_info_cmd() -> None:
    info = sdk.api_info()
    if info is None:
        click.echo(f'API server at {sdk.api_server_url()}: unreachable')
    else:
        click.echo(f'API server at {sdk.api_server_url()}: {info}')


@api.command(name='status')
@click.option('--limit', type=int, default=30)
def api_status(limit) -> None:
    rows = sdk.api_status(limit)
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('REQUEST', 'NAME', 'USER', 'STATUS'):
        table.add_column(col)
    for r in rows:
        table.add_row(r['request_id'], r['name'], r.get('user') or '-',
                      r['status'])
    Console().print(table)


@api.command(name='logs')
@click.argument('request_id')
def api_logs(request_id) -> None:
    try:
        sdk.stream_and_get(request_id)
    except exceptions.SkyError as e:
        _err(str(e))


@api.command(name='cancel')
@click.argument('request_id')
def api_cancel(request_id) -> None:
    if sdk.api_cancel(request_id):
        click.echo('Cancelled.')
    else:
        click.echo('Request already finished.')




# ---------------------------------------------------------------------------
# jobs group (managed jobs)
# ---------------------------------------------------------------------------
@cli.group()
def jobs() -> None:
    """Managed jobs: auto-recovering jobs on (preemptible) clusters."""


@jobs.command(name='launch')
@click.argument('entrypoint', required=False)
@_add_options(_task_options)
@click.option('--pool', default=None,
              help='Run on a pre-provisioned worker pool.')
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_launch_cmd(entrypoint, name, workdir, infra, gpus, cpus, memory,
                    num_nodes, use_spot, env, env_file, pool, detach_run,
                    yes) -> None:
    """Launch a managed job (survives preemption via auto-recovery).

    A YAML with multiple documents is a PIPELINE: stages run
    sequentially, one cluster each, with per-stage recovery."""
    stages = None
    if entrypoint and (entrypoint.endswith(('.yaml', '.yml')) and
                       os.path.exists(os.path.expanduser(entrypoint))):
        docs = [c for c in common_utils.read_yaml_all(
            os.path.expanduser(entrypoint)) if c]
        if len(docs) > 1:
            # Per-stage resources come from the YAML; resource flags
            # would be ambiguous (which stage?) — reject instead of
            # silently ignoring them. --env applies to every stage.
            if any(v for v in (workdir, infra, gpus, cpus, memory,
                               num_nodes)) or use_spot is not None:
                raise click.UsageError(
                    'Pipelines take per-stage resources from the YAML; '
                    '--workdir/--infra/--gpus/--cpus/--memory/'
                    '--num-nodes/--use-spot do not apply.')
            env_overrides = _merged_env(env, env_file)
            from skypilot_tpu import task as task_lib
            stages = [task_lib.Task.from_yaml_config(d, env_overrides)
                      for d in docs]
    if stages is not None:
        if not yes:
            click.confirm(
                f'Launch {len(stages)}-stage pipeline '
                f'({", ".join(t.name or "?" for t in stages)})?',
                default=True, abort=True)
        result = sdk.get(sdk.jobs_launch(
            stages, name=name or stages[0].name, pool=pool))
        job_id = result['job_id']
        click.echo(f'Managed pipeline {job_id} submitted '
                   f'({len(stages)} stages).')
        if not detach_run:
            sdk.jobs_logs(job_id)
        return
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, env_file=env_file)
    if not yes:
        click.confirm(f'Launch managed job {task.name or "task"}?',
                      default=True, abort=True)
    result = sdk.get(sdk.jobs_launch(task, name=task.name, pool=pool))
    job_id = result['job_id']
    click.echo(f'Managed job {job_id} submitted.')
    if not detach_run:
        sdk.jobs_logs(job_id)


@jobs.group(name='pool')
def jobs_pool() -> None:
    """Worker pools that managed jobs reuse (skip provisioning)."""


@jobs_pool.command(name='apply')
@click.argument('entrypoint', required=False)
@click.option('--pool-name', '-p', 'pool_name', required=True)
@click.option('--workers', type=int, default=1)
@_add_options(_task_options)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_pool_apply_cmd(entrypoint, pool_name, workers, name, workdir,
                        infra, gpus, cpus, memory, num_nodes, use_spot,
                        env, env_file, yes) -> None:
    """Provision a pool of worker clusters from a resources template."""
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, cmd='true',
                       env_file=env_file)
    task.run = None
    if not yes:
        click.confirm(f'Provision pool {pool_name} ({workers} workers)?',
                      default=True, abort=True)
    result = sdk.stream_and_get(sdk.jobs_pool_apply(task, pool_name,
                                                    workers))
    click.echo(f'Pool {pool_name} ready: {result["workers"]}')


@jobs_pool.command(name='ls')
def jobs_pool_ls_cmd() -> None:
    rows = sdk.get(sdk.jobs_pool_ls())
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'WORKERS', 'BUSY'):
        table.add_column(col)
    for r in rows:
        table.add_row(r['name'], str(r['num_workers']),
                      str(r['busy_workers']))
    Console().print(table)


@jobs_pool.command(name='down')
@click.argument('pool_name')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_pool_down_cmd(pool_name, yes) -> None:
    if not yes:
        click.confirm(f'Tear down pool {pool_name}?', abort=True)
    sdk.stream_and_get(sdk.jobs_pool_down(pool_name))
    click.echo(f'Pool {pool_name} torn down.')


@jobs_pool.command(name='status')
@click.argument('pool_name')
def jobs_pool_status_cmd(pool_name) -> None:
    """Per-worker view: cluster status + the job each worker runs."""
    rows = sdk.get(sdk.jobs_pool_status(pool_name))
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('WORKER', 'STATUS', 'JOB'):
        table.add_column(col)
    for r in rows:
        table.add_row(r['worker'], r['status'],
                      str(r['job_id']) if r['job_id'] is not None else '-')
    Console().print(table)


@jobs.group(name='group')
def jobs_group() -> None:
    """Co-scheduled job groups (RL actor/learner, disaggregated serve)."""


@jobs_group.command(name='launch')
@click.argument('yaml_files', nargs=-1, required=True)
@click.option('--group-name', '-n', 'group_name', required=True)
def jobs_group_launch_cmd(yaml_files, group_name) -> None:
    """Launch one managed job per YAML, atomically, with each task's
    env carrying every peer's head address."""
    from skypilot_tpu import task as task_lib
    tasks = [task_lib.Task.from_yaml(f) for f in yaml_files]
    result = sdk.get(sdk.jobs_group_launch(tasks, group_name))
    click.echo(f'Group {group_name}: jobs {result["job_ids"]} submitted.')


@jobs_group.command(name='status')
@click.argument('group_name')
def jobs_group_status_cmd(group_name) -> None:
    rows = sdk.get(sdk.jobs_group_status(group_name))
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('ID', 'NAME', 'CLUSTER', 'ADDR', 'STATUS'):
        table.add_column(col)
    for r in rows:
        table.add_row(str(r['job_id']), r['name'] or '-',
                      r['cluster_name'] or '-', r['head_ip'] or '-',
                      r['status'])
    Console().print(table)


@jobs_group.command(name='cancel')
@click.argument('group_name')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_group_cancel_cmd(group_name, yes) -> None:
    if not yes:
        click.confirm(f'Cancel all jobs in group {group_name}?', abort=True)
    cancelled = sdk.get(sdk.jobs_group_cancel(group_name))
    click.echo(f'Cancelled jobs: {cancelled}')


@jobs.command(name='queue')
@click.option('--refresh', '-r', is_flag=True, default=False)
@click.option('--skip-finished', '-s', is_flag=True, default=False)
def jobs_queue_cmd(refresh, skip_finished) -> None:
    """Show managed jobs."""
    rows = sdk.get(sdk.jobs_queue(refresh, skip_finished))
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('ID', 'NAME', 'CLUSTER', 'STAGE', 'STATUS', 'RECOVERIES',
                'ERROR'):
        table.add_column(col)
    for j in rows:
        table.add_row(str(j['job_id']), j.get('name') or '-',
                      j.get('cluster_name') or '-',
                      j.get('stage') or '-', j['status'],
                      str(j['recovery_count']),
                      (j.get('last_error') or '')[:40])
    Console().print(table)


@jobs.command(name='cancel')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_cancel_cmd(job_ids, all_jobs, yes) -> None:
    """Cancel managed job(s)."""
    if not job_ids and not all_jobs:
        _err('specify job ids or --all')
    if not yes:
        click.confirm('Cancel?', abort=True)
    cancelled = sdk.get(sdk.jobs_cancel(list(job_ids) or None, all_jobs))
    click.echo(f'Cancelled: {cancelled}')


@jobs.command(name='logs')
@click.argument('job_id', type=int)
@click.option('--no-follow', is_flag=True, default=False)
def jobs_logs_cmd(job_id, no_follow) -> None:
    """Stream a managed job's controller log."""
    sdk.jobs_logs(job_id, follow=not no_follow)


# ---------------------------------------------------------------------------
# serve group
# ---------------------------------------------------------------------------
@cli.group()
def serve() -> None:
    """Serving: replicated services with load balancing + autoscaling."""


@serve.command(name='up')
@click.argument('entrypoint')
@click.option('--service-name', '-s', default=None)
@_add_options(_task_options)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_up_cmd(entrypoint, service_name, name, workdir, infra, gpus, cpus,
                 memory, num_nodes, use_spot, env, env_file, yes) -> None:
    """Bring up a service from a task YAML with a service: section."""
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, env_file=env_file)
    service_name = service_name or task.name or 'service'
    if not yes:
        click.confirm(f'Bring up service {service_name}?', default=True,
                      abort=True)
    result = sdk.get(sdk.serve_up(task, service_name))
    click.echo(f'Service {service_name} starting; endpoint: '
               f'{result["endpoint"]}')


@serve.command(name='status')
@click.argument('services', nargs=-1)
def serve_status_cmd(services) -> None:
    """Show services and their replicas."""
    rows = sdk.get(sdk.serve_status(list(services) or None))
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'VERSION', 'STATUS', 'ENDPOINT', 'REPLICAS'):
        table.add_column(col)
    for s in rows:
        ready = sum(1 for r in s['replicas'] if r['status'] == 'READY')
        table.add_row(s['name'], str(s['version']), s['status'],
                      s['endpoint'] or '-',
                      f"{ready}/{len(s['replicas'])}")
    Console().print(table)
    for s in rows:
        if s['replicas']:
            rep_table = Table(box=None, title=f"{s['name']} replicas")
            for col in ('ID', 'STATUS', 'ENDPOINT', 'CLUSTER'):
                rep_table.add_column(col)
            for r in s['replicas']:
                rep_table.add_row(str(r['replica_id']), r['status'],
                                  r.get('endpoint') or '-',
                                  r['cluster_name'])
            Console().print(rep_table)


@serve.command(name='update')
@click.argument('service_name')
@click.argument('entrypoint')
@_add_options(_task_options)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_update_cmd(service_name, entrypoint, name, workdir, infra, gpus,
                     cpus, memory, num_nodes, use_spot, env, env_file,
                     yes) -> None:
    """Update a service to a new task version."""
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, env_file=env_file)
    if not yes:
        click.confirm(f'Update service {service_name}?', abort=True)
    result = sdk.get(sdk.serve_update(task, service_name))
    click.echo(f'Service {service_name} updated to v{result["version"]}.')


@serve.command(name='logs')
@click.argument('service_name')
@click.option('--no-follow', is_flag=True, default=False)
@click.option('--replica', type=int, default=None,
              help='Stream this replica\'s job log instead of the '
                   'controller log.')
def serve_logs_cmd(service_name, no_follow, replica) -> None:
    """Stream a service's controller log (or one replica's job log)."""
    if replica is not None:
        rows = sdk.get(sdk.serve_status([service_name]))
        if not rows:
            _err(f'service {service_name!r} not found')
        match = [r for r in rows[0]['replicas']
                 if r['replica_id'] == replica]
        if not match:
            known = sorted(r['replica_id'] for r in rows[0]['replicas'])
            _err(f'no replica {replica} (known: {known})')
        try:
            sdk.tail_logs(match[0]['cluster_name'], None,
                          follow=not no_follow)
        except exceptions.ClusterDoesNotExist:
            _err(f'replica {replica} has no live cluster '
                 f'({match[0]["status"]})')
        return
    sdk.serve_logs(service_name, follow=not no_follow,
                   output=sys.stdout)


@serve.command(name='down')
@click.argument('service_names', nargs=-1)
@click.option('--all', '-a', 'all_services', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False)
def serve_down_cmd(service_names, all_services, yes, purge) -> None:
    """Tear down service(s)."""
    if all_services:
        service_names = [s['name'] for s in sdk.get(sdk.serve_status())]
        if not service_names:
            click.echo('No services.')
            return
    if not service_names:
        raise click.UsageError('specify service name(s) or --all')
    if not yes:
        click.confirm(f'Tear down {", ".join(service_names)}?', abort=True)
    for s in service_names:
        sdk.get(sdk.serve_down(s, purge=purge))
        click.echo(f'Service {s} torn down.')


@serve.command(name='sync-down-logs')
@click.argument('service_name')
def serve_sync_down_logs_cmd(service_name) -> None:
    """Download a service's controller log to ~/sky_logs_download/."""
    dst_dir = os.path.expanduser(
        os.path.join('~/sky_logs_download', 'serve'))
    os.makedirs(dst_dir, exist_ok=True)
    dst = os.path.join(dst_dir, f'{service_name}.log')
    with open(dst, 'w', encoding='utf-8') as f:
        sdk.serve_logs(service_name, follow=False, output=f)
    click.echo(f'Log synced to {dst}')


# ---------------------------------------------------------------------------
# recipes / volumes / debug
# ---------------------------------------------------------------------------
@cli.group()
def recipes() -> None:
    """Curated runnable recipes (bundled example YAMLs)."""


@recipes.command(name='list')
def recipes_list() -> None:
    from skypilot_tpu.recipes import core as recipes_core
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'ACCELERATOR', 'DESCRIPTION'):
        table.add_column(col)
    for r in recipes_core.list_recipes():
        table.add_row(r['name'], r['accelerator'], r['description'][:70])
    Console().print(table)


@recipes.command(name='show')
@click.argument('name')
def recipes_show(name) -> None:
    from skypilot_tpu.recipes import core as recipes_core
    try:
        path = recipes_core.get_recipe_path(name)
    except FileNotFoundError as e:
        _err(str(e))
    with open(path, 'r', encoding='utf-8') as f:
        click.echo(f.read())


@cli.group()
def volumes() -> None:
    """Persistent volumes."""


@volumes.command(name='apply')
@click.argument('name')
@click.option('--size', type=int, required=True, help='Size in GB.')
@click.option('--infra', default=None)
@click.option('--type', 'volume_type', default='pd-balanced')
def volumes_apply(name, size, infra, volume_type) -> None:
    from skypilot_tpu.volumes import core as volumes_core
    cfg = volumes_core.apply(name, size, infra, volume_type)
    click.echo(f'Volume {name} ({cfg["size_gb"]}GB {cfg["type"]}) ready.')


@volumes.command(name='ls')
def volumes_ls() -> None:
    from skypilot_tpu.volumes import core as volumes_core
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'SIZE', 'TYPE', 'INFRA', 'STATUS'):
        table.add_column(col)
    for v in volumes_core.ls():
        table.add_row(v['name'], f"{v['size_gb']}GB", v['type'],
                      v['infra'], v['status'])
    Console().print(table)


@volumes.command(name='delete')
@click.argument('name')
@click.option('--yes', '-y', is_flag=True, default=False)
def volumes_delete(name, yes) -> None:
    if not yes:
        click.confirm(f'Delete volume {name}?', abort=True)
    from skypilot_tpu.volumes import core as volumes_core
    volumes_core.delete(name)
    click.echo(f'Volume {name} deleted.')


@cli.command(name='debug-dump')
@click.option('--output', '-o', default='skypilot-debug.tar.gz')
def debug_dump(output) -> None:
    """Bundle local state + logs for a bug report (secrets redacted:
    the state DBs carry no credential material)."""
    import tarfile
    from skypilot_tpu import constants as const
    home = const.sky_home()
    if not os.path.isdir(home):
        _err(f'No state at {home}.')
    with tarfile.open(output, 'w:gz') as tar:
        for sub in ('state.db', 'managed_jobs.db', 'serve.db',
                    'api_server/requests.db', 'api_server/server.log',
                    'managed_jobs_logs', 'serve_logs', 'usage'):
            path = os.path.join(home, sub)
            if os.path.exists(path):
                tar.add(path, arcname=sub)
    click.echo(f'Wrote {output}.')


@cli.command(name='trace')
@click.argument('trace_id')
@click.option('--endpoint', '-e', 'endpoints', multiple=True,
              required=True, metavar='HOST:PORT',
              help='A serving process to query (repeat for each: '
                   'the LB, the prefill replica, the decode peer). '
                   'Each answers GET /debug/trace/<id> with its own '
                   'spans of the trace.')
@click.option('--output', '-o', default=None, metavar='FILE',
              help='Write the merged Chrome-trace JSON here '
                   '(default: stdout).')
@click.option('--timeout', type=float, default=5.0,
              help='Per-endpoint HTTP timeout, seconds.')
def trace_cmd(trace_id, endpoints, output, timeout) -> None:
    """Merge one request's spans across serving processes.

    A request traced at --trace-sample crosses up to three processes
    (LB route -> prefill replica -> decode peer), each recording its
    own spans under the shared trace id from the x-skypilot-trace
    header. This fetches every process's view, de-duplicates, sorts
    by wall clock, and emits ONE Chrome-trace JSON — load it in
    chrome://tracing or ui.perfetto.dev (`pid` rows = processes).
    """
    import json as json_lib

    import requests as requests_lib

    from skypilot_tpu.observability import tracing
    bodies = []
    misses = []
    for ep in endpoints:
        base = ep if '://' in ep else f'http://{ep}'
        url = f'{base.rstrip("/")}/debug/trace/{trace_id}'
        try:
            resp = requests_lib.get(url, timeout=timeout)
        except requests_lib.RequestException as e:
            misses.append(f'{ep}: {type(e).__name__}')
            continue
        if resp.status_code == 200:
            bodies.append(resp.json())
        else:
            # 404 is normal: a process the trace never crossed.
            misses.append(f'{ep}: HTTP {resp.status_code}')
    if not bodies:
        _err(f'trace {trace_id} not found on any endpoint'
             f'{" (" + "; ".join(misses) + ")" if misses else ""}')
    merged = tracing.merge_traces(bodies)
    text = json_lib.dumps(merged, indent=2)
    n = len(merged['traceEvents'])
    if output:
        with open(output, 'w', encoding='utf-8') as f:
            f.write(text)
        click.echo(f'Wrote {n} spans from {len(bodies)}/'
                   f'{len(endpoints)} endpoints to {output}.')
    else:
        click.echo(text)
    if misses:
        click.secho('; '.join(misses), fg='yellow', err=True)


@cli.group()
def batch() -> None:
    """Batch: map a task over dataset shards on a worker pool."""


@batch.command(name='launch')
@click.argument('entrypoint')
@click.option('--batch-name', '-b', 'batch_name', required=True)
@click.option('--input', 'input_path', required=True,
              help='JSONL input file.')
@click.option('--output-dir', required=True)
@click.option('--workers', type=int, default=2)
@click.option('--shards', type=int, default=None)
@_add_options(_task_options)
@click.option('--yes', '-y', is_flag=True, default=False)
def batch_launch_cmd(entrypoint, batch_name, input_path, output_dir,
                     workers, shards, name, workdir, infra, gpus, cpus,
                     memory, num_nodes, use_spot, env, env_file,
                     yes) -> None:
    """Launch a batch job over a JSONL dataset."""
    task = _build_task(entrypoint, name, workdir, infra, gpus, cpus, memory,
                       num_nodes, use_spot, env, env_file=env_file)
    if not yes:
        click.confirm(f'Launch batch {batch_name} ({workers} workers)?',
                      default=True, abort=True)
    result = sdk.get(sdk.batch_launch(task, batch_name, input_path,
                                      output_dir, workers, shards))
    click.echo(f'Batch {batch_name}: {result["num_shards"]} shards on '
               f'{result["num_workers"]} workers.')


@batch.command(name='ls')
def batch_ls_cmd() -> None:
    rows = sdk.get(sdk.batch_ls())
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('NAME', 'STATUS', 'SHARDS', 'FAILED', 'WORKERS'):
        table.add_column(col)
    for r in rows:
        table.add_row(r['name'], r['status'],
                      f"{r['shards_done']}/{r['num_shards']}",
                      str(r['shards_failed']), str(r['num_workers']))
    Console().print(table)


@batch.command(name='cancel')
@click.argument('batch_name')
@click.option('--yes', '-y', is_flag=True, default=False)
def batch_cancel_cmd(batch_name, yes) -> None:
    if not yes:
        click.confirm(f'Cancel batch {batch_name}?', abort=True)
    if sdk.get(sdk.batch_cancel(batch_name)):
        click.echo('Cancelled.')
    else:
        click.echo('Already finished or not found.')




@cli.group(name='users', invoke_without_command=True)
@click.pass_context
def users_cmd(ctx) -> None:
    """Users, roles, and service-account tokens (admin)."""
    if ctx.invoked_subcommand is not None:
        return
    rows = sdk.users_ls()
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('USER', 'ROLE', 'REQUESTS', 'LAST SEEN'):
        table.add_column(col)
    for r in rows:
        last = datetime.datetime.fromtimestamp(
            r['last_seen']).strftime('%m-%d %H:%M') if r['last_seen'] else '-'
        table.add_row(r['name'], r.get('role') or 'user',
                      str(r['request_count']), last)
    Console().print(table)


@users_cmd.command(name='role')
@click.argument('user')
@click.argument('role', type=click.Choice(['admin', 'user']))
def users_role_cmd(user: str, role: str) -> None:
    """Set USER's role (admin only)."""
    sdk.users_set_role(user, role)
    click.echo(f'{user}: role={role}')


@users_cmd.group(name='token')
def users_token_cmd() -> None:
    """Service-account tokens: server-derived identity for the API."""


@users_token_cmd.command(name='issue')
@click.argument('user')
@click.option('--role', default='user',
              type=click.Choice(['admin', 'user']))
def token_issue_cmd(user: str, role: str) -> None:
    """Mint a token for USER; the cleartext is printed ONCE."""
    out = sdk.token_issue(user, role)
    click.echo(f'token_id: {out["token_id"]}')
    click.echo(f'token:    {out["token"]}')
    click.echo('Store it now — it is not retrievable later. Clients '
               'present it via SKYPILOT_API_TOKEN or '
               'api_server.auth_token in config.')


@users_token_cmd.command(name='ls')
def token_ls_cmd() -> None:
    """List issued tokens (hashes only)."""
    from rich.console import Console
    from rich.table import Table
    table = Table(box=None)
    for col in ('TOKEN ID', 'USER', 'CREATED', 'LAST USED', 'REVOKED'):
        table.add_column(col)
    for t in sdk.token_ls():
        created = datetime.datetime.fromtimestamp(
            t['created_at']).strftime('%m-%d %H:%M')
        last = (datetime.datetime.fromtimestamp(
            t['last_used_at']).strftime('%m-%d %H:%M')
            if t['last_used_at'] else '-')
        table.add_row(t['token_id'], t['user_hash'], created, last,
                      'yes' if t['revoked'] else '')
    Console().print(table)


@users_token_cmd.command(name='revoke')
@click.argument('token_id')
def token_revoke_cmd(token_id: str) -> None:
    """Revoke a token by its id."""
    if sdk.token_revoke(token_id):
        click.echo('Revoked.')
    else:
        click.echo('No such token.', err=True)


# ---------------------------------------------------------------------------
# config / workspaces / ssh-node-pool / dashboard
# ---------------------------------------------------------------------------
@cli.group()
def config() -> None:
    """View and edit the layered config (server < user < project)."""


@config.command(name='list')
def config_list() -> None:
    """Dump the effective merged config as YAML."""
    import yaml as yaml_lib
    from skypilot_tpu import sky_config
    merged = sky_config.to_dict()
    if not merged:
        click.echo('# (empty config)')
        return
    click.echo(yaml_lib.safe_dump(merged, default_flow_style=False,
                                  sort_keys=False).rstrip())


@config.command(name='get')
@click.argument('key')
def config_get(key) -> None:
    """Read a dotted key, e.g. `stpu config get gcp.project_id`."""
    import yaml as yaml_lib
    from skypilot_tpu import sky_config
    sentinel = object()
    value = sky_config.get_nested(tuple(key.split('.')), sentinel)
    if value is sentinel:
        _err(f'{key}: not set')
    if isinstance(value, (dict, list)):
        click.echo(yaml_lib.safe_dump(value, default_flow_style=False,
                                      sort_keys=False).rstrip())
    else:
        click.echo(value)


@config.command(name='set')
@click.argument('key')
@click.argument('value')
def config_set(key, value) -> None:
    """Set a dotted key in the user config file (YAML-parsed value)."""
    import yaml as yaml_lib
    from skypilot_tpu import sky_config
    try:
        parsed = yaml_lib.safe_load(value)
    except yaml_lib.YAMLError:
        parsed = value
    try:
        path = sky_config.set_nested(tuple(key.split('.')), parsed)
    except Exception as e:  # pylint: disable=broad-except
        _err(f'rejected: {e}')
    click.echo(f'{key} = {parsed!r}  ({path})')


@config.command(name='unset')
@click.argument('key')
def config_unset(key) -> None:
    """Remove a dotted key from the user config file."""
    from skypilot_tpu import sky_config
    path = sky_config.set_nested(tuple(key.split('.')), None)
    click.echo(f'{key} removed  ({path})')


@cli.group()
def workspaces() -> None:
    """Multi-tenant namespaces with per-workspace cloud allow-lists."""


@workspaces.command(name='ls')
def workspaces_ls() -> None:
    from skypilot_tpu.workspaces import core as ws_core
    from rich.console import Console
    from rich.table import Table
    active = ws_core.active_workspace()
    table = Table(box=None)
    for col in ('NAME', 'ACTIVE', 'ALLOWED CLOUDS'):
        table.add_column(col)
    for name, ws in sorted(ws_core.get_workspaces().items()):
        allowed = (ws or {}).get('allowed_clouds')
        table.add_row(name, '*' if name == active else '',
                      ', '.join(allowed) if allowed else '(all)')
    Console().print(table)


@workspaces.command(name='show')
@click.argument('name', required=False)
def workspaces_show(name) -> None:
    import yaml as yaml_lib
    from skypilot_tpu.workspaces import core as ws_core
    try:
        ws = ws_core.get_workspace(name)
    except exceptions.SkyError as e:
        _err(str(e))
    click.echo(yaml_lib.safe_dump(
        {name or ws_core.active_workspace(): ws or {}},
        default_flow_style=False).rstrip())


@workspaces.command(name='switch')
@click.argument('name')
def workspaces_switch(name) -> None:
    """Make NAME the active workspace (persisted in user config)."""
    from skypilot_tpu import sky_config
    from skypilot_tpu.workspaces import core as ws_core
    try:
        ws_core.get_workspace(name)
    except exceptions.SkyError as e:
        _err(str(e))
    sky_config.set_nested(('active_workspace',), name)
    click.echo(f'Active workspace: {name}')


@cli.group(name='ssh-node-pool')
def ssh_node_pool() -> None:
    """Bring-your-own machines declared in ssh_node_pools.yaml."""


@ssh_node_pool.command(name='ls')
def ssh_node_pool_ls() -> None:
    from skypilot_tpu.clouds import ssh as ssh_cloud
    from rich.console import Console
    from rich.table import Table
    pools = ssh_cloud.load_pools()
    if not pools:
        click.echo(f'No pools declared ({ssh_cloud.POOLS_PATH}).')
        return
    table = Table(box=None)
    for col in ('POOL', 'HOSTS', 'USER', 'IDENTITY'):
        table.add_column(col)
    for name, pool in sorted(pools.items()):
        hosts = pool.get('hosts', [])
        users = {h.get('user') for h in hosts}
        keys = {h.get('identity_file') for h in hosts}
        table.add_row(
            name, str(len(hosts)),
            users.pop() if len(users) == 1 else '(mixed)',
            keys.pop() if len(keys) == 1 else '(mixed)')
    Console().print(table)


@ssh_node_pool.command(name='check')
@click.argument('pool', required=False)
@click.option('--timeout', type=float, default=10.0)
def ssh_node_pool_check(pool, timeout) -> None:
    """SSH-probe every host of a pool (`true` over the declared auth)."""
    from skypilot_tpu.clouds import ssh as ssh_cloud
    from skypilot_tpu.utils import command_runner
    from skypilot_tpu.utils import subprocess_utils
    pools = ssh_cloud.load_pools()
    if pool is not None:
        if pool not in pools:
            _err(f'pool {pool!r} not declared; known: '
                 + ', '.join(sorted(pools)))
        pools = {pool: pools[pool]}

    def _probe(host):
        runner = command_runner.SSHCommandRunner(
            (host['ip'], host.get('port', 22)), host.get('user', 'root'),
            host.get('identity_file', '~/.ssh/id_ed25519'))
        rc, _, err = runner.run('true', stream_logs=False,
                                require_outputs=True, timeout=timeout)
        return rc, (err or '').strip()

    for name, p in sorted(pools.items()):
        hosts = p.get('hosts', [])
        results = subprocess_utils.run_in_parallel(_probe, hosts)
        for host, (rc, err) in zip(hosts, results):
            ok = 'OK' if rc == 0 else f'FAIL ({err[:60]})'
            click.echo(f'{name}\t{host["ip"]}\t{ok}')


@cli.command()
@click.option('--no-open', is_flag=True, default=False,
              help='Print the URL instead of opening a browser.')
def dashboard(no_open) -> None:
    """Open the live web dashboard served by the API server."""
    url = sdk.api_server_url().rstrip('/') + '/dashboard'
    click.echo(url)
    if not no_open:
        import webbrowser
        webbrowser.open(url)


@api.command(name='login')
@click.option('--endpoint', '-e', default=None,
              help='API server URL, e.g. http://host:46580')
@click.option('--token', default=None,
              help='Service-account token (or set SKYPILOT_API_TOKEN).')
@click.option('--oauth', 'use_oauth', is_flag=True, default=False,
              help='Browser OIDC login (needs oauth.issuer/client_id).')
@click.option('--issuer', default=None, help='Override oauth.issuer.')
@click.option('--client-id', default=None,
              help='Override oauth.client_id.')
@click.option('--no-browser', is_flag=True, default=False,
              help='Print the authorize URL instead of opening it.')
def api_login(endpoint, token, use_oauth, issuer, client_id,
              no_browser) -> None:
    """Point this client at a remote API server (persisted in config)."""
    from skypilot_tpu import sky_config
    if not endpoint and not use_oauth:
        raise click.UsageError('pass --endpoint and/or --oauth')
    if endpoint:
        endpoint = endpoint.rstrip('/')
        sky_config.set_nested(('api_server', 'endpoint'), endpoint)
    if token:
        sky_config.set_nested(('api_server', 'auth_token'), token)
    if use_oauth:
        import requests as _requests
        from skypilot_tpu.client import oauth as oauth_lib
        try:
            oauth_lib.login(issuer=issuer, client_id=client_id,
                            open_browser=not no_browser)
        except (exceptions.SkyError, _requests.RequestException) as e:
            _err(f'OAuth login failed: {e}')
        click.echo('OAuth login complete; token cached.')
    if endpoint:
        info = sdk.api_info(endpoint)
        if info is None:
            click.secho(f'Warning: {endpoint} is not reachable right now.',
                        fg='yellow', err=True)
        click.echo(f'Logged in to {endpoint}.')


@api.command(name='logout')
def api_logout() -> None:
    """Drop the cached OAuth token."""
    from skypilot_tpu.client import oauth as oauth_lib
    click.echo('Logged out.' if oauth_lib.logout()
               else 'No cached OAuth token.')


@recipes.command(name='launch')
@click.argument('name')
@click.option('--cluster', '-c', default=None)
@click.option('--env', multiple=True, help='KEY=VAL or KEY (inherit).')
@click.option('--yes', '-y', is_flag=True, default=False)
def recipes_launch(name, cluster, env, yes) -> None:
    """Launch a bundled recipe by name (see `stpu recipes list`)."""
    from skypilot_tpu.recipes import core as recipes_core
    try:
        path = recipes_core.get_recipe_path(name)
    except FileNotFoundError as e:
        _err(str(e))
    from skypilot_tpu import task as task_lib
    task = task_lib.Task.from_yaml_config(
        common_utils.read_yaml(path), _parse_env(list(env or [])))
    if not yes:
        r = sorted(str(x) for x in task.resources)
        click.confirm(f'Launch recipe {name} on {r}?', default=True,
                      abort=True)
    request_id = sdk.launch(task, cluster_name=cluster, detach_run=True)
    result = sdk.stream_and_get(request_id)
    if result and result.get('job_id') is not None:
        cname = (result.get('handle') or {}).get('cluster_name') or cluster
        sdk.tail_logs(cname, result['job_id'])


# ---------------------------------------------------------------------------
# long-tail commands (reference: sky local up/down, sky ssh up/down,
# shell completion install, jobs pool logs)
# ---------------------------------------------------------------------------
_LOCAL_DEV_CLUSTER = 'stpu-local'


@cli.group()
def local() -> None:
    """Manage the local dev cluster (sandbox hosts, no cloud)."""


@local.command(name='up')
@click.option('--nodes', type=int, default=1,
              help='Number of sandbox hosts.')
def local_up(nodes) -> None:
    """Provision the local dev cluster (`stpu-local`) for fast
    iteration: later `stpu exec stpu-local ...` runs skip provisioning
    (reference: `sky local up` kind cluster)."""
    from skypilot_tpu import task as task_lib
    task = task_lib.Task(run='true', num_nodes=nodes)
    from skypilot_tpu import resources as resources_lib
    task.set_resources(resources_lib.Resources(infra='local'))
    request_id = sdk.launch(task, cluster_name=_LOCAL_DEV_CLUSTER,
                            detach_run=True)
    sdk.stream_and_get(request_id)
    click.echo(f'Local dev cluster {_LOCAL_DEV_CLUSTER!r} is up '
               f'({nodes} host(s)).')


@local.command(name='down')
def local_down() -> None:
    """Tear down the local dev cluster."""
    sdk.get(sdk.down(_LOCAL_DEV_CLUSTER))
    click.echo(f'Local dev cluster {_LOCAL_DEV_CLUSTER!r} removed.')


@ssh_node_pool.command(name='up')
@click.argument('pool')
def ssh_node_pool_up(pool) -> None:
    """Pre-deploy the runtime to every pool host (warms launches:
    the per-launch package rsync becomes a no-op delta)."""
    from skypilot_tpu.clouds import ssh as ssh_cloud
    from skypilot_tpu.provision import instance_setup
    from skypilot_tpu.utils import command_runner
    from skypilot_tpu.utils import subprocess_utils
    pools = ssh_cloud.load_pools()
    if pool not in pools:
        _err(f'pool {pool!r} not declared; known: '
             + ', '.join(sorted(pools)))
    hosts = pools[pool]['hosts']

    def deploy(host):
        runner = command_runner.SSHCommandRunner(
            (host['ip'], host['port']), host['user'],
            host['identity_file'])
        try:
            rc = runner.run('python3 --version', stream_logs=False)
            if rc != 0:
                return f'FAIL (no python3, rc={rc})'
            instance_setup.deploy_package(runner)
        except Exception as e:  # pylint: disable=broad-except
            # Per-host outcome rows: one bad host must not abort (or
            # hide) the rest of the fan-out.
            return f'FAIL ({str(e)[:80]})'
        return 'OK'

    results = subprocess_utils.run_in_parallel(deploy, hosts)
    for host, outcome in zip(hosts, results):
        click.echo(f'{pool}\t{host["ip"]}\t{outcome}')


@ssh_node_pool.command(name='down')
@click.argument('pool')
@click.option('--yes', '-y', is_flag=True, default=False)
def ssh_node_pool_down(pool, yes) -> None:
    """Stop agents and remove the deployed runtime from pool hosts."""
    from skypilot_tpu.clouds import ssh as ssh_cloud
    from skypilot_tpu.provision.ssh import instance as ssh_instance
    from skypilot_tpu.utils import command_runner
    from skypilot_tpu.utils import subprocess_utils
    pools = ssh_cloud.load_pools()
    if pool not in pools:
        _err(f'pool {pool!r} not declared; known: '
             + ', '.join(sorted(pools)))
    busy = [cluster for cluster, entry in
            ssh_instance.list_allocations().items()
            if entry.get('pool') == pool]
    if busy:
        _err(f'pool {pool!r} still hosts cluster(s) {sorted(busy)}; '
             'run `stpu down` on them first.')
    if not yes:
        click.confirm(f'Remove the runtime from all hosts of {pool!r}?',
                      default=True, abort=True)
    from skypilot_tpu.provision import instance_setup
    pkg_dir = instance_setup.remote_pkg_dir()

    def teardown(host):
        runner = command_runner.SSHCommandRunner(
            (host['ip'], host['port']), host['user'],
            host['identity_file'])
        try:
            rc = runner.run('pkill -f skypilot_tpu.agent.agent || true; '
                            f'rm -rf {pkg_dir}', stream_logs=False)
        except Exception as e:  # pylint: disable=broad-except
            return f'FAIL ({str(e)[:80]})'
        return 'OK' if rc == 0 else f'FAIL (rc={rc})'

    results = subprocess_utils.run_in_parallel(
        teardown, pools[pool]['hosts'])
    for host, outcome in zip(pools[pool]['hosts'], results):
        click.echo(f'{pool}\t{host["ip"]}\t{outcome}')


@jobs_pool.command(name='logs')
@click.argument('pool_name')
@click.option('--worker', '-w', type=int, default=0,
              help='Worker index within the pool.')
@click.option('--job-id', type=int, default=None,
              help='Job id on that worker (default: latest).')
def jobs_pool_logs_cmd(pool_name, worker, job_id) -> None:
    """Tail a pool worker's job log (workers are ordinary clusters
    named pool-<name>-w<i>)."""
    from skypilot_tpu.jobs import pools as pools_lib
    cluster = pools_lib.worker_cluster(pool_name, worker)
    sdk.tail_logs(cluster, job_id)


@cli.command()
@click.argument('shell', type=click.Choice(['bash', 'zsh', 'fish']))
def completion(shell) -> None:
    """Print the shell-completion script (add to your rc file):

    bash: eval "$(stpu completion bash)"
    """
    from click.shell_completion import get_completion_class
    comp_cls = get_completion_class(shell)
    if comp_cls is None:
        _err(f'No completion support for {shell!r}.')
    comp = comp_cls(cli, {}, 'stpu', '_STPU_COMPLETE')
    click.echo(comp.source())


def main() -> None:
    try:
        cli()
    except exceptions.SkyError as e:
        _err(str(e))


if __name__ == '__main__':
    main()
