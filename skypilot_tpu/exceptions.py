"""Typed exceptions for skypilot_tpu.

Mirrors the error taxonomy of the reference orchestrator
(`sky/exceptions.py`) with the subset that matters for a TPU-first
build: resource availability (carrying failover history), cluster
lifecycle, job/serve state, and API-server request errors.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional


class SkyError(Exception):
    """Base class for all framework errors."""


# ---------------------------------------------------------------------------
# Resources / optimizer
# ---------------------------------------------------------------------------
class ResourcesUnavailableError(SkyError):
    """No cloud/region/zone can satisfy the request.

    Carries the failover history so callers (managed jobs, retrying
    provisioner) can distinguish capacity errors from config errors.
    Reference: sky/exceptions.py ResourcesUnavailableError.
    """

    def __init__(self,
                 message: str,
                 no_failover: bool = False,
                 failover_history: Optional[List[Exception]] = None,
                 blocked_cloud: Optional[str] = None) -> None:
        super().__init__(message)
        self.no_failover = no_failover
        self.failover_history: List[Exception] = failover_history or []
        # Set when an account-level (scope='cloud') error stopped the
        # walk: retrying THIS cloud is pointless, but a caller that can
        # re-optimize (managed jobs) may succeed on another cloud by
        # blocking this one.
        self.blocked_cloud = blocked_cloud

    def with_failover_history(
            self, failover_history: List[Exception]
    ) -> 'ResourcesUnavailableError':
        self.failover_history = failover_history
        return self


class ResourcesMismatchError(SkyError):
    """Requested resources do not match the existing cluster."""


class InvalidResourcesError(SkyError):
    """The resources spec itself is invalid (bad accelerator, topology...)."""


class NoCloudAccessError(SkyError):
    """No cloud is enabled / credentials available."""


class NotSupportedError(SkyError):
    """Operation not supported (e.g. stop on a TPU pod slice)."""


# ---------------------------------------------------------------------------
# Cluster lifecycle
# ---------------------------------------------------------------------------
class ClusterNotUpError(SkyError):
    """Cluster is not in UP status."""

    def __init__(self, message: str, cluster_status: Any = None,
                 handle: Any = None) -> None:
        super().__init__(message)
        self.cluster_status = cluster_status
        self.handle = handle


class ClusterDoesNotExist(SkyError):
    """Cluster name not found in global state."""


class ClusterOwnerIdentityMismatchError(SkyError):
    """Current user identity does not own the cluster."""


class ClusterSetUpError(SkyError):
    """Runtime setup (agent bootstrap) on the cluster failed."""


class ProvisionerError(SkyError):
    """Low-level provision failure for one zone attempt.

    `category` + `scope` steer the failover engine (reference:
    FailoverCloudErrorHandlerV2's error→blocklist mapping,
    cloud_vm_ray_backend.py:522). `scope` is the blast radius of the
    block — 'zone' | 'region' | 'cloud' | 'abort' — normally supplied
    by the per-cloud pattern table (provision/failover_patterns.py);
    when omitted it derives from the category:
      capacity   → zone   (stockout: try the next zone)
      transient  → zone   (hiccup: walking on is safe)
      quota      → region (quotas are regional)
      permission → abort  (no location fixes credentials)
      config     → abort  (the request itself is invalid)
    """

    CAPACITY = 'capacity'
    QUOTA = 'quota'
    PERMISSION = 'permission'
    CONFIG = 'config'
    TRANSIENT = 'transient'

    _DEFAULT_SCOPE = {
        CAPACITY: 'zone',
        TRANSIENT: 'zone',
        QUOTA: 'region',
        PERMISSION: 'abort',
        CONFIG: 'abort',
    }

    def __init__(self, message: str,
                 errors: Optional[List[Dict[str, Any]]] = None,
                 category: str = 'transient',
                 scope: Optional[str] = None):
        super().__init__(message)
        self.errors = errors or []
        self.category = category
        self.scope = scope or self._DEFAULT_SCOPE.get(category, 'zone')

    @property
    def no_failover(self) -> bool:
        return self.scope == 'abort'

    @property
    def blocks_region(self) -> bool:
        return self.scope == 'region'

    @property
    def blocks_cloud(self) -> bool:
        return self.scope == 'cloud'


class ProvisionPrechecksError(SkyError):
    """Prechecks (quota, permissions) failed before provisioning."""

    def __init__(self, reasons: List[Exception]) -> None:
        super().__init__(str([str(r) for r in reasons]))
        self.reasons = reasons


class CommandError(SkyError):
    """A remote command returned non-zero.

    Reference: sky/exceptions.py CommandError.
    """

    def __init__(self, returncode: int, command: str, error_msg: str,
                 detailed_reason: Optional[str] = None) -> None:
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        if len(command) > 100:
            command = command[:100] + '...'
        super().__init__(
            f'Command {command} failed with return code {returncode}.'
            f'\n{error_msg}')


class FetchClusterInfoError(SkyError):
    """Failed to query cluster info from the cloud."""

    class Reason(enum.Enum):
        HEAD = 'HEAD'
        WORKER = 'WORKER'

    def __init__(self, reason: 'FetchClusterInfoError.Reason') -> None:
        super().__init__(f'Failed to fetch info for {reason.value} node(s).')
        self.reason = reason


# ---------------------------------------------------------------------------
# Jobs
# ---------------------------------------------------------------------------
class JobNotFoundError(SkyError):
    pass


class ManagedJobReachedMaxRetriesError(SkyError):
    """Managed job exhausted max_restarts_on_errors."""


class ManagedJobStatusError(SkyError):
    """Managed job in unexpected state."""


class JobExitNonZeroError(SkyError):
    """User job exited with a non-zero return code."""

    def __init__(self, message: str, returncode: int) -> None:
        super().__init__(message)
        self.returncode = returncode


# ---------------------------------------------------------------------------
# Serve
# ---------------------------------------------------------------------------
class ServeUserTerminatedError(SkyError):
    pass


class ServiceNotFoundError(SkyError):
    pass


# ---------------------------------------------------------------------------
# Storage
# ---------------------------------------------------------------------------
class StorageError(SkyError):
    pass


class StorageSpecError(StorageError):
    pass


class StorageBucketCreateError(StorageError):
    pass


class StorageBucketGetError(StorageError):
    pass


class StorageModeError(StorageError):
    pass


class StorageUploadError(StorageError):
    pass


# ---------------------------------------------------------------------------
# API server / requests
# ---------------------------------------------------------------------------
class ApiServerConnectionError(SkyError):
    def __init__(self, server_url: str) -> None:
        super().__init__(
            f'Could not connect to API server at {server_url}. '
            'Start one with `stpu api start`.')
        self.server_url = server_url


class RequestNotFoundError(SkyError):
    pass


class PermissionDeniedError(SkyError):
    """401/403 from the API server (RBAC or bad/missing token)."""


class ApiVersionMismatchError(SkyError):
    """Client and server API versions cannot interoperate."""


class RequestCancelled(SkyError):
    pass


class ApiRequestError(SkyError):
    """Server returned an error for a request; wraps the remote traceback."""

    def __init__(self, message: str, remote_traceback: Optional[str] = None,
                 error_type: Optional[str] = None) -> None:
        super().__init__(message)
        self.remote_traceback = remote_traceback
        self.error_type = error_type


# ---------------------------------------------------------------------------
# Config / validation
# ---------------------------------------------------------------------------
class InvalidSkyPilotConfigError(SkyError):
    pass


class InvalidTaskYAMLError(SkyError):
    pass


class UserRequestRejectedByPolicy(SkyError):
    """Admin policy rejected the request."""


# ---------------------------------------------------------------------------
# Serialization helpers (errors crossing the client/server HTTP boundary)
# ---------------------------------------------------------------------------
_EXC_REGISTRY: Dict[str, type] = {}


def _register_all() -> None:
    for obj in list(globals().values()):
        if isinstance(obj, type) and issubclass(obj, Exception):
            _EXC_REGISTRY[obj.__name__] = obj


def serialize_exception(exc: BaseException) -> Dict[str, Any]:
    """JSON-serializable form of an exception for the request DB."""
    import traceback
    return {
        'type': type(exc).__name__,
        'message': str(exc),
        'traceback': ''.join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)),
    }


def deserialize_exception(payload: Dict[str, Any]) -> Exception:
    exc_type = _EXC_REGISTRY.get(payload.get('type', ''), None)
    msg = payload.get('message', '')
    if exc_type is None:
        return ApiRequestError(f"{payload.get('type')}: {msg}",
                               remote_traceback=payload.get('traceback'),
                               error_type=payload.get('type'))
    try:
        exc = exc_type(msg)
    except TypeError:
        exc = ApiRequestError(f"{payload.get('type')}: {msg}",
                              remote_traceback=payload.get('traceback'),
                              error_type=payload.get('type'))
    return exc


_register_all()
