"""Usage telemetry: redacted event records, local-first.

Reference: sky/usage/usage_lib.py — redacted usage messages shipped to
a Loki endpoint. This build records the same shape of events to a
local JSONL ring (`~/.sky-tpu/usage/usage.jsonl`); a remote endpoint
can be configured (`usage: {endpoint: ...}`) and is a no-op in
zero-egress environments. Opt out with
SKYPILOT_DISABLE_USAGE_COLLECTION=1.

Redaction: only coarse fields leave the call site — command name,
cloud, accelerator type, node counts, durations, exception *type*.
Never YAML contents, env values, paths, or names.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Iterator, Optional

from skypilot_tpu import constants
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils.env_options import Options

_MAX_BYTES = 4 * 1024 * 1024


def _usage_path() -> str:
    return os.path.join(constants.sky_home(), 'usage', 'usage.jsonl')


def enabled() -> bool:
    return not Options.DISABLE_LOGGING.get()


def record_event(event: str, **fields: Any) -> None:
    if not enabled():
        return
    payload: Dict[str, Any] = {
        'time': time.time(),
        'event': event,
        'run_id': common_utils.get_usage_run_id(),
        'user': common_utils.get_user_hash(),  # hashed, not the username
        'version': '0.1.0',
    }
    payload.update(fields)
    path = _usage_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if os.path.exists(path) and os.path.getsize(path) > _MAX_BYTES:
            os.replace(path, path + '.1')
        with open(path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(payload) + '\n')
    except OSError:
        pass
    endpoint = os.environ.get('SKYPILOT_USAGE_ENDPOINT')
    if endpoint:
        with contextlib.suppress(Exception):
            import requests
            requests.post(endpoint, json=payload, timeout=2)


@contextlib.contextmanager
def entrypoint(name: str, **fields: Any) -> Iterator[None]:
    """Time an entrypoint and record outcome (redacted)."""
    start = time.time()
    error_type: Optional[str] = None
    try:
        yield
    except BaseException as e:
        error_type = type(e).__name__
        raise
    finally:
        record_event('entrypoint', name=name,
                     duration=round(time.time() - start, 3),
                     error=error_type, **fields)
