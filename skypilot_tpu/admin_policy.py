"""Admin policy: pluggable request mutation/validation hook.

Reference: sky/admin_policy.py — every launch passes a UserRequest
through the configured AdminPolicy, which may mutate the dag/config
or reject the request. Configured by import path in config:
  admin_policy: mypkg.policies.MyPolicy
"""
from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_config


@dataclasses.dataclass
class RequestOptions:
    cluster_name: Optional[str] = None
    idle_minutes_to_autostop: Optional[int] = None
    down: bool = False
    dryrun: bool = False


@dataclasses.dataclass
class UserRequest:
    dag: dag_lib.Dag
    skypilot_config: Dict[str, Any]
    request_options: Optional[RequestOptions] = None
    at_client_side: bool = False


@dataclasses.dataclass
class MutatedUserRequest:
    dag: dag_lib.Dag
    skypilot_config: Dict[str, Any]


class AdminPolicy:
    """Subclass and implement validate_and_mutate."""

    @classmethod
    def validate_and_mutate(cls,
                            user_request: UserRequest) -> MutatedUserRequest:
        raise NotImplementedError


def load_policy() -> Optional[type]:
    path = sky_config.get_nested(('admin_policy',))
    if not path:
        return None
    module_path, class_name = path.rsplit('.', 1)
    module = importlib.import_module(module_path)
    policy_cls = getattr(module, class_name)
    if not (isinstance(policy_cls, type) and
            issubclass(policy_cls, AdminPolicy)):
        raise exceptions.InvalidSkyPilotConfigError(
            f'admin_policy {path!r} is not an AdminPolicy subclass.')
    return policy_cls


def apply(dag: dag_lib.Dag,
          request_options: Optional[RequestOptions] = None) -> dag_lib.Dag:
    """Apply the configured policy to a dag (no-op if none configured).

    Reference: sky/utils/admin_policy_utils.py, applied at
    sky/execution.py:299.
    """
    policy_cls = load_policy()
    if policy_cls is None:
        return dag
    request = UserRequest(dag=dag, skypilot_config=sky_config.to_dict(),
                          request_options=request_options)
    try:
        mutated = policy_cls.validate_and_mutate(request)
    except exceptions.UserRequestRejectedByPolicy:
        raise
    except Exception as e:  # pylint: disable=broad-except
        raise exceptions.UserRequestRejectedByPolicy(
            f'Admin policy {policy_cls.__name__} failed: {e}') from e
    mutated.dag.policy_applied = True
    return mutated.dag
