"""Run-with-log + rotation-safe tail/follow.

Reference: sky/skylet/log_lib.py (909 LoC): subprocess with
tee-to-file + streaming; follow survives truncation/rotation.
"""
from __future__ import annotations

import os
import subprocess
import time
from typing import Dict, Iterator, Optional, Tuple


def run_bash_with_log(script: str, log_path: str,
                      env: Optional[Dict[str, str]] = None,
                      cwd: Optional[str] = None) -> subprocess.Popen:
    """Spawn `bash -c script` with stdout+stderr appended to log_path."""
    log_path = os.path.expanduser(log_path)
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    log_file = open(log_path, 'ab', buffering=0)
    full_env = dict(os.environ)
    if env:
        full_env.update({k: str(v) for k, v in env.items()})
    if cwd is not None:
        cwd = os.path.expanduser(cwd)
        os.makedirs(cwd, exist_ok=True)
    proc = subprocess.Popen(
        ['bash', '-c', script],
        stdout=log_file,
        stderr=subprocess.STDOUT,
        stdin=subprocess.DEVNULL,
        env=full_env,
        cwd=cwd,
        start_new_session=True,   # own process group: clean cancel
    )
    # The fd is inherited by the child; close our handle.
    log_file.close()
    return proc


def tail_logs(log_path: str, *, follow: bool = False,
              from_start: bool = True, tail_lines: int = 0,
              stop_condition=None, poll_interval: float = 0.2
              ) -> Iterator[str]:
    """Yield log lines; with follow=True keep reading until
    stop_condition() returns True and the file is drained. Reopens on
    truncation (rotation-safe: reference log_lib.py:444-555)."""
    log_path = os.path.expanduser(log_path)
    # Wait briefly for the file to appear (job may still be starting).
    deadline = time.time() + (30 if follow else 0)
    while not os.path.exists(log_path):
        if time.time() > deadline:
            return
        time.sleep(poll_interval)

    f = open(log_path, 'r', encoding='utf-8', errors='replace')
    try:
        if tail_lines > 0:
            lines = f.readlines()[-tail_lines:]
            yield from lines
        elif not from_start:
            f.seek(0, os.SEEK_END)
        while True:
            pos = f.tell()
            line = f.readline()
            if line:
                yield line
                continue
            if not follow:
                break
            # Detect truncation/rotation.
            try:
                size = os.path.getsize(log_path)
            except OSError:
                size = 0
            if size < pos:
                f.close()
                f = open(log_path, 'r', encoding='utf-8', errors='replace')
                continue
            if stop_condition is not None and stop_condition():
                # Drain whatever arrived in the race window.
                rest = f.read()
                if rest:
                    yield rest
                break
            time.sleep(poll_interval)
    finally:
        f.close()
