"""On-cluster job queue: sqlite table + FIFO scheduler.

Reference: sky/skylet/job_lib.py (1459 LoC) — `jobs` + `pending_jobs`
sqlite tables, JobStatus state machine INIT→SETTING_UP→PENDING→
RUNNING→terminal, FIFOScheduler spawning queued driver processes.

TPU-native difference: the driver program is not a Ray driver; it is
`agent.job_driver`, which gang-executes the per-rank command on every
host agent of the slice (all-or-nothing, kill-all-on-failure).
"""
from __future__ import annotations

import enum
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.robustness import train_guard
from skypilot_tpu.utils import db_utils
from skypilot_tpu.utils import subprocess_utils


class JobStatus(enum.Enum):
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'
    # Typed trainer exits (robustness/train_guard.py): terminal for
    # the ON-CLUSTER job, but the managed-jobs controller maps them
    # to its recovery path (relaunch) instead of user failure.
    PREEMPTED = 'PREEMPTED'            # graceful preemption-notice exit
    WATCHDOG_ABORT = 'WATCHDOG_ABORT'  # hung step/loader, aborted

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.FAILED_SETUP, JobStatus.CANCELLED,
                        JobStatus.PREEMPTED, JobStatus.WATCHDOG_ABORT)

    def is_recoverable(self) -> bool:
        """Terminal exits the managed-jobs controller should answer
        with a PREEMPTING -> RECOVERING relaunch, NOT count against
        the user-failure restart budget."""
        return self in (JobStatus.PREEMPTED, JobStatus.WATCHDOG_ABORT)

    @classmethod
    def terminal_statuses(cls) -> List['JobStatus']:
        return [s for s in cls if s.is_terminal()]


#: Typed rank exit code -> job status (the trainer's side of the
#: contract; anything unlisted stays a plain FAILED).
_EXIT_CODE_STATUS = {
    train_guard.EXIT_PREEMPTED_GRACEFUL: JobStatus.PREEMPTED,
    train_guard.EXIT_WATCHDOG_ABORT: JobStatus.WATCHDOG_ABORT,
}


def status_for_exit_code(rc: int) -> Optional[JobStatus]:
    """Typed status for a rank's exit code, or None for untyped."""
    return _EXIT_CODE_STATUS.get(rc)


_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_name TEXT,
    username TEXT,
    submitted_at REAL,
    start_at REAL,
    end_at REAL,
    status TEXT,
    run_timestamp TEXT,
    resources TEXT,
    pid INTEGER DEFAULT -1,
    spec TEXT,
    log_dir TEXT
);
"""


class JobTable:
    """One per agent home dir."""

    def __init__(self, agent_home: str) -> None:
        self._db = db_utils.SQLiteDB(
            os.path.join(os.path.expanduser(agent_home), 'jobs.db'),
            _CREATE_SQL)

    # -- CRUD ---------------------------------------------------------------
    def add_job(self, job_name: Optional[str], username: str,
                spec: Dict[str, Any], log_dir: str) -> int:
        run_timestamp = time.strftime('sky-%Y-%m-%d-%H-%M-%S-%f')
        with self._db.conn() as conn:
            cur = conn.execute(
                'INSERT INTO jobs (job_name, username, submitted_at, status, '
                'run_timestamp, spec, log_dir) VALUES (?,?,?,?,?,?,?)',
                (job_name, username, time.time(), JobStatus.PENDING.value,
                 run_timestamp, json.dumps(spec), log_dir))
            return int(cur.lastrowid)

    def get_job(self, job_id: int) -> Optional[Dict[str, Any]]:
        row = self._db.query_one('SELECT * FROM jobs WHERE job_id=?',
                                 (job_id,))
        return self._decode(row) if row else None

    def get_jobs(self, status: Optional[List[JobStatus]] = None,
                 limit: int = 0) -> List[Dict[str, Any]]:
        sql = 'SELECT * FROM jobs'
        params: tuple = ()
        if status:
            marks = ','.join('?' * len(status))
            sql += f' WHERE status IN ({marks})'
            params = tuple(s.value for s in status)
        sql += ' ORDER BY job_id DESC'
        if limit:
            sql += f' LIMIT {int(limit)}'
        return [self._decode(r) for r in self._db.query(sql, params)]

    @staticmethod
    def _decode(row: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(row)
        out['status'] = JobStatus(out['status'])
        out['spec'] = json.loads(out['spec']) if out.get('spec') else {}
        return out

    # -- state transitions ----------------------------------------------------
    def set_status(self, job_id: int, status: JobStatus) -> None:
        sets = ['status=?']
        params: List[Any] = [status.value]
        if status == JobStatus.SETTING_UP:
            sets.append('start_at=?')
            params.append(time.time())
        if status == JobStatus.RUNNING:
            sets.append('start_at=COALESCE(start_at, ?)')
            params.append(time.time())
        if status.is_terminal():
            sets.append('end_at=?')
            params.append(time.time())
        params.append(job_id)
        self._db.execute(f'UPDATE jobs SET {", ".join(sets)} WHERE job_id=?',
                         tuple(params))

    def set_pid(self, job_id: int, pid: int) -> None:
        self._db.execute('UPDATE jobs SET pid=? WHERE job_id=?',
                         (pid, job_id))

    # -- scheduling -----------------------------------------------------------
    def next_pending(self) -> Optional[Dict[str, Any]]:
        rows = self.get_jobs(status=[JobStatus.PENDING])
        return rows[-1] if rows else None  # lowest job_id first

    def any_active(self) -> bool:
        return bool(self.get_jobs(status=[JobStatus.SETTING_UP,
                                          JobStatus.RUNNING,
                                          JobStatus.INIT]))

    def reconcile(self) -> None:
        """Fix statuses of jobs whose driver process died (crash safety)."""
        for job in self.get_jobs(status=[JobStatus.SETTING_UP,
                                         JobStatus.RUNNING]):
            pid = job.get('pid') or -1
            if pid > 0 and not subprocess_utils.process_alive(pid):
                status_file = os.path.join(job['log_dir'], 'driver_status')
                final = JobStatus.FAILED
                try:
                    with open(status_file, 'r', encoding='utf-8') as f:
                        final = JobStatus(f.read().strip())
                except (OSError, ValueError):
                    pass
                if not final.is_terminal():
                    final = JobStatus.FAILED
                self.set_status(job['job_id'], final)

    def last_activity_time(self) -> float:
        """Most recent job activity (for autostop idle tracking)."""
        row = self._db.query_one(
            'SELECT MAX(MAX(COALESCE(end_at,0), COALESCE(start_at,0), '
            'submitted_at)) AS t FROM jobs')
        if row is None or row['t'] is None:
            return 0.0
        return float(row['t'])
