"""AgentClient: the API server's handle to a cluster's head agent.

Reference analog: the SkyletClient gRPC wrapper
(sky/backends/cloud_vm_ray_backend.py:2888-3086). Plain HTTP here; the
transport address comes from the cluster handle (direct IP:port, or a
localhost tunnel endpoint for SSH-only clusters).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import requests

from skypilot_tpu import exceptions
from skypilot_tpu.agent import job_lib


class AgentClient:
    """Talks to one agent.

    `addr` may be a list of candidate `host:port` endpoints tried in
    order (internal IP first, external as fallback) — the first one
    that answers is cached for the rest of the client's life. `secret`
    is the per-cluster token sent as X-Agent-Token on every request.
    """

    def __init__(self, addr: Union[str, Sequence[str]],
                 timeout: float = 30.0,
                 secret: Optional[str] = None) -> None:
        addrs = [addr] if isinstance(addr, str) else list(addr)
        # De-dup, preserving order (internal == external on localhost).
        self.candidates = list(dict.fromkeys(a for a in addrs if a))
        if not self.candidates:
            raise ValueError('AgentClient needs at least one address')
        self.base = f'http://{self.candidates[0]}'
        self._probed = len(self.candidates) == 1
        self.timeout = timeout
        self.headers = {'X-Agent-Token': secret} if secret else {}

    def _probe(self) -> None:
        """Pick the first reachable candidate (short connect timeout).

        If nothing answers (agent still booting), stays unprobed so the
        next call re-tries — a boot-time failure must not pin a dead
        endpoint for the client's lifetime.
        """
        if self._probed:
            return
        for cand in self.candidates:
            try:
                resp = requests.get(f'http://{cand}/health', timeout=(3, 5))
                if resp.status_code != 200:
                    continue  # some other service answered on this port
                self.base = f'http://{cand}'
                self._probed = True
                return
            except requests.RequestException:
                continue

    def _get(self, path: str, **kw) -> Dict[str, Any]:
        self._probe()
        resp = requests.get(f'{self.base}{path}', timeout=self.timeout,
                            headers=self.headers, **kw)
        resp.raise_for_status()
        return resp.json()

    def _post(self, path: str, payload: Optional[Dict] = None
              ) -> Dict[str, Any]:
        self._probe()
        resp = requests.post(f'{self.base}{path}', json=payload or {},
                             timeout=self.timeout, headers=self.headers)
        resp.raise_for_status()
        return resp.json()

    # -- health ---------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        return self._get('/health')

    def wait_until_healthy(self, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if self.health().get('status') == 'ok':
                    return True
            except requests.RequestException:
                pass
            time.sleep(1.0)
        return False

    # -- jobs -------------------------------------------------------------------
    def submit_job(self, name: Optional[str], username: str,
                   spec: Dict[str, Any]) -> int:
        out = self._post('/jobs/submit', {
            'name': name, 'username': username, 'spec': spec})
        return int(out['job_id'])

    def get_job(self, job_id: int) -> Optional[Dict[str, Any]]:
        try:
            out = self._get(f'/jobs/{job_id}')
        except requests.HTTPError as e:
            if e.response is not None and e.response.status_code == 404:
                return None
            raise
        out['status'] = job_lib.JobStatus(out['status'])
        return out

    def get_jobs(self, status: Optional[List[job_lib.JobStatus]] = None,
                 limit: int = 0) -> List[Dict[str, Any]]:
        params = {}
        if status:
            params['status'] = ','.join(s.value for s in status)
        if limit:
            params['limit'] = str(limit)
        out = self._get('/jobs', params=params)
        rows = out['jobs']
        for r in rows:
            r['status'] = job_lib.JobStatus(r['status'])
        return rows

    def cancel_job(self, job_id: int) -> None:
        self._post(f'/jobs/{job_id}/cancel')

    def wait_job(self, job_id: int,
                 timeout: Optional[float] = None) -> job_lib.JobStatus:
        deadline = time.time() + timeout if timeout else None
        while True:
            job = self.get_job(job_id)
            if job is None:
                raise exceptions.JobNotFoundError(str(job_id))
            if job['status'].is_terminal():
                return job['status']
            if deadline and time.time() > deadline:
                raise TimeoutError(f'job {job_id} still {job["status"]}')
            time.sleep(2.0)

    def stream_job_logs(self, job_id: int, *, follow: bool = True,
                        tail: int = 0,
                        rank: Optional[int] = None) -> Iterator[str]:
        params = {'follow': '1' if follow else '0'}
        if tail:
            params['tail'] = str(tail)
        if rank is not None:
            params['rank'] = str(rank)
        self._probe()
        with requests.get(f'{self.base}/jobs/{job_id}/logs', params=params,
                          stream=True, timeout=(30, None),
                          headers=self.headers) as resp:
            resp.raise_for_status()
            for line in resp.iter_lines(decode_unicode=True):
                yield line + '\n'

    # -- autostop ---------------------------------------------------------------
    def set_autostop(self, idle_minutes: Optional[int], down: bool,
                     hook: Optional[str] = None) -> None:
        if idle_minutes is None or idle_minutes < 0:
            self._post('/autostop', {})
        else:
            self._post('/autostop', {'idle_minutes': idle_minutes,
                                     'down': down, 'hook': hook})
