"""The on-cluster agent: job queue + exec + autostop, one per host.

Replaces the reference's skylet (sky/skylet/skylet.py) *and* its
embedded Ray cluster (SURVEY §7: a TPU slice is already a gang, so
gang exec is agent-to-agent fan-out, not Ray placement groups):

  - every host of every slice runs one agent (HTTP, stdlib-only so a
    bare TPU VM image can run it);
  - the head host's agent additionally owns the cluster job queue
    (job_lib), an event loop (scheduler step + autostop, reference
    skylet events), and spawns one `job_driver` process per job;
  - worker endpoints (/exec/*) run one rank's bash script with logs.

Endpoints:
  GET  /health                       liveness + version
  POST /jobs/submit                  queue a job (head only)
  GET  /jobs                         list jobs
  GET  /jobs/<id>                    job record
  POST /jobs/<id>/cancel             cancel pending/running job
  GET  /jobs/<id>/logs?follow=1      combined log stream
  POST /autostop                     set autostop policy
  POST /exec                         run a rank script (worker-level)
  GET  /exec/<id>/status             rank status
  GET  /exec/<id>/logs?follow=1      rank log stream
  POST /exec/<id>/cancel             kill rank process group
"""
from __future__ import annotations

import argparse
import hmac
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from skypilot_tpu import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import log_lib
from skypilot_tpu.utils import subprocess_utils

_EVENT_INTERVAL_SECONDS = 2.0


def secret_path(home: str) -> str:
    return os.path.join(home, 'agent_secret')


def read_secret(home: str) -> Optional[str]:
    try:
        with open(secret_path(home), 'r', encoding='utf-8') as f:
            value = f.read().strip()
            return value or None
    except OSError:
        return None


class AgentState:

    def __init__(self, home: str, cluster_name: str, is_head: bool) -> None:
        self.home = os.path.abspath(os.path.expanduser(home))
        os.makedirs(self.home, exist_ok=True)
        self.cluster_name = cluster_name
        try:  # visible to job_driver (log shipping paths)
            with open(os.path.join(self.home, 'cluster_name'), 'w',
                      encoding='utf-8') as f:
                f.write(cluster_name)
        except OSError:
            pass
        self.is_head = is_head
        # Per-cluster shared secret, written at provision time. When
        # present, every endpoint except GET /health requires it (the
        # reference only reaches skylet over SSH/authed gRPC — an open
        # /exec port would be remote code execution).
        self.secret = read_secret(self.home)
        self.jobs = job_lib.JobTable(self.home) if is_head else None
        self.started_at = time.time()
        # rank executions: job_id -> {'proc': Popen, 'rc': Optional[int]}
        self.execs: Dict[int, Dict[str, Any]] = {}
        self.execs_lock = threading.Lock()
        self.autostop: Optional[Dict[str, Any]] = None
        self._load_autostop()

    # -- autostop persistence -------------------------------------------------
    def _autostop_path(self) -> str:
        return os.path.join(self.home, 'autostop.json')

    def _load_autostop(self) -> None:
        try:
            with open(self._autostop_path(), 'r', encoding='utf-8') as f:
                self.autostop = json.load(f)
        except (OSError, ValueError):
            self.autostop = None

    def set_autostop(self, config: Optional[Dict[str, Any]]) -> None:
        self.autostop = config
        if config is None:
            try:
                os.remove(self._autostop_path())
            except OSError:
                pass
        else:
            with open(self._autostop_path(), 'w', encoding='utf-8') as f:
                json.dump(config, f)

    def exec_dir(self, job_id: int) -> str:
        d = os.path.join(self.home, 'tasks', str(job_id))
        os.makedirs(d, exist_ok=True)
        return d


STATE: Optional[AgentState] = None


# ---------------------------------------------------------------------------
# Event loop (reference: skylet events — JobSchedulerEvent, StopEvent)
# ---------------------------------------------------------------------------
def _scheduler_step(state: AgentState) -> None:
    jobs = state.jobs
    assert jobs is not None
    jobs.reconcile()
    if jobs.any_active():
        return
    job = jobs.next_pending()
    if job is None:
        return
    jobs.set_status(job['job_id'], job_lib.JobStatus.INIT)
    log_path = os.path.join(state.home, f'driver-{job["job_id"]}.log')
    pid = subprocess_utils.launch_daemon(
        [sys.executable, '-m', 'skypilot_tpu.agent.job_driver',
         '--home', state.home, '--job-id', str(job['job_id'])],
        log_path=log_path,
        env=dict(os.environ))
    jobs.set_pid(job['job_id'], pid)


def _autostop_step(state: AgentState) -> None:
    cfg = state.autostop
    if not cfg or not state.is_head:
        return
    idle_minutes = cfg.get('idle_minutes', -1)
    if idle_minutes is None or idle_minutes < 0:
        return
    assert state.jobs is not None
    if state.jobs.any_active() or state.jobs.next_pending() is not None:
        return
    last = max(state.jobs.last_activity_time(), state.started_at)
    if time.time() - last < idle_minutes * 60:
        return
    # Fire the stop/down hook: the cluster stops itself. The hook
    # command is injected at provision time (reference:
    # autostop_lib executes sky.stop from the cluster itself).
    hook = cfg.get('hook')
    marker = os.path.join(state.home, 'autostop_fired')
    if os.path.exists(marker):
        return
    with open(marker, 'w', encoding='utf-8') as f:
        f.write(str(time.time()))
    if hook:
        subprocess.Popen(['bash', '-c', hook], start_new_session=True)


def _event_loop(state: AgentState) -> None:
    while True:
        try:
            if state.is_head:
                _scheduler_step(state)
                _autostop_step(state)
        except Exception as e:  # pylint: disable=broad-except
            print(f'agent event loop error: {e!r}', file=sys.stderr)
        time.sleep(_EVENT_INTERVAL_SECONDS)


# ---------------------------------------------------------------------------
# HTTP handler
# ---------------------------------------------------------------------------
class Handler(BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.0'  # close-at-end simplifies log streaming

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- helpers -------------------------------------------------------------
    def _json(self, obj: Any, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get('Content-Length', 0))
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))

    # -- routing -------------------------------------------------------------
    def do_GET(self):  # noqa: N802
        try:
            self._route('GET')
        except BrokenPipeError:
            pass
        except Exception as e:  # pylint: disable=broad-except
            self._safe_error(e)

    def do_POST(self):  # noqa: N802
        try:
            self._route('POST')
        except BrokenPipeError:
            pass
        except Exception as e:  # pylint: disable=broad-except
            self._safe_error(e)

    def _safe_error(self, e: Exception) -> None:
        try:
            self._json({'error': f'{type(e).__name__}: {e}'}, code=500)
        except Exception:  # pylint: disable=broad-except
            pass

    def _authorized(self, method: str, parts) -> bool:
        if STATE.secret is None:
            return True
        if method == 'GET' and parts == ['health']:
            return True  # liveness probes stay secretless
        presented = self.headers.get('X-Agent-Token', '')
        return hmac.compare_digest(presented, STATE.secret)

    def _route(self, method: str) -> None:
        assert STATE is not None
        url = urlparse(self.path)
        parts = [p for p in url.path.split('/') if p]
        query = {k: v[0] for k, v in parse_qs(url.query).items()}

        if not self._authorized(method, parts):
            self._json({'error': 'missing or bad X-Agent-Token'}, code=401)
            return

        if method == 'GET' and parts == ['health']:
            self._json({
                'status': 'ok',
                'version': constants.AGENT_VERSION,
                'cluster': STATE.cluster_name,
                'is_head': STATE.is_head,
                'uptime': time.time() - STATE.started_at,
            })
            return

        if parts and parts[0] == 'jobs' and STATE.jobs is not None:
            self._route_jobs(method, parts, query)
            return
        if parts and parts[0] == 'exec':
            self._route_exec(method, parts, query)
            return
        if method == 'POST' and parts == ['autostop']:
            body = self._read_body()
            STATE.set_autostop(body or None)
            self._json({'ok': True})
            return
        self._json({'error': f'no route {method} {url.path}'}, code=404)

    # -- job queue (head) ------------------------------------------------------
    def _route_jobs(self, method: str, parts, query) -> None:
        assert STATE is not None and STATE.jobs is not None
        jobs = STATE.jobs
        if method == 'POST' and parts == ['jobs', 'submit']:
            body = self._read_body()
            log_dir = os.path.join(STATE.home, 'job_logs')
            job_id = jobs.add_job(body.get('name'),
                                  body.get('username', 'unknown'),
                                  body['spec'], log_dir)
            log_dir = os.path.join(log_dir, str(job_id))
            with jobs._db.conn() as conn:  # pylint: disable=protected-access
                conn.execute('UPDATE jobs SET log_dir=? WHERE job_id=?',
                             (log_dir, job_id))
            self._json({'job_id': job_id})
            return
        if method == 'GET' and parts == ['jobs']:
            status = None
            if 'status' in query:
                status = [job_lib.JobStatus(s)
                          for s in query['status'].split(',')]
            rows = jobs.get_jobs(status=status,
                                 limit=int(query.get('limit', 0)))
            for r in rows:
                r['status'] = r['status'].value
            self._json({'jobs': rows})
            return
        if len(parts) >= 2 and parts[0] == 'jobs':
            try:
                job_id = int(parts[1])
            except ValueError:
                self._json({'error': f'bad job id {parts[1]}'}, code=400)
                return
            job = jobs.get_job(job_id)
            if job is None:
                self._json({'error': f'no job {job_id}'}, code=404)
                return
            if method == 'GET' and len(parts) == 2:
                job['status'] = job['status'].value
                self._json(job)
                return
            if method == 'POST' and parts[2:] == ['cancel']:
                self._cancel_job(job)
                self._json({'ok': True})
                return
            if method == 'GET' and parts[2:] == ['logs']:
                self._stream_job_logs(job, query)
                return
        self._json({'error': 'bad jobs route'}, code=404)

    def _cancel_job(self, job: Dict[str, Any]) -> None:
        assert STATE is not None and STATE.jobs is not None
        status: job_lib.JobStatus = job['status']
        if status.is_terminal():
            return
        pid = job.get('pid') or -1
        STATE.jobs.set_status(job['job_id'], job_lib.JobStatus.CANCELLED)
        if pid > 0:
            # Driver traps SIGTERM → cancels all rank execs.
            subprocess_utils.kill_process_tree(pid, sig=signal.SIGTERM)

    def _stream_job_logs(self, job: Dict[str, Any], query) -> None:
        assert STATE is not None and STATE.jobs is not None
        follow = query.get('follow', '0') == '1'
        tail = int(query.get('tail', 0))
        # ?rank=i streams one rank's own file (job_driver writes
        # rank-<i>.log per rank + the combined run.log).
        rank = query.get('rank')
        if rank not in (None, ''):
            if not str(rank).isdigit():
                self._json({'error': f'bad rank {rank!r}'}, code=400)
                return
            filename = f'rank-{int(rank)}.log'
        else:
            filename = 'run.log'
        log_path = os.path.join(job['log_dir'], filename)
        if filename != 'run.log' and not os.path.exists(log_path):
            self._json({'error': f'no log for rank {rank}'}, code=404)
            return
        self.send_response(200)
        self.send_header('Content-Type', 'text/plain; charset=utf-8')
        self.end_headers()
        job_id = job['job_id']

        def finished() -> bool:
            j = STATE.jobs.get_job(job_id)
            return j is None or j['status'].is_terminal()

        for line in log_lib.tail_logs(log_path, follow=follow,
                                      tail_lines=tail,
                                      stop_condition=finished):
            self.wfile.write(line.encode('utf-8', errors='replace'))
            self.wfile.flush()

    # -- rank exec (all hosts) --------------------------------------------------
    def _route_exec(self, method: str, parts, query) -> None:
        assert STATE is not None
        if method == 'POST' and parts == ['exec']:
            body = self._read_body()
            job_id = int(body['job_id'])
            d = STATE.exec_dir(job_id)
            log_path = os.path.join(d, 'rank.log')
            rc_path = os.path.join(d, 'rc')
            try:
                os.remove(rc_path)
            except OSError:
                pass
            script = body['script']
            wrapped = (f'{script}\nrc=$?\n'
                       f'echo $rc > {rc_path}\nexit $rc')
            proc = log_lib.run_bash_with_log(
                wrapped, log_path, env=body.get('env'),
                cwd=body.get('cwd'))
            with STATE.execs_lock:
                STATE.execs[job_id] = {'proc': proc, 'rc': None}

            def reap():
                rc = proc.wait()
                with STATE.execs_lock:
                    STATE.execs[job_id]['rc'] = rc

            threading.Thread(target=reap, daemon=True).start()
            self._json({'pid': proc.pid})
            return

        if len(parts) >= 2 and parts[0] == 'exec':
            job_id = int(parts[1])
            d = STATE.exec_dir(job_id)
            if method == 'GET' and parts[2:] == ['status']:
                rc = self._exec_rc(job_id)
                self._json({'running': rc is None, 'rc': rc})
                return
            if method == 'POST' and parts[2:] == ['cancel']:
                with STATE.execs_lock:
                    entry = STATE.execs.get(job_id)
                if entry and entry['rc'] is None:
                    try:
                        os.killpg(os.getpgid(entry['proc'].pid),
                                  signal.SIGTERM)
                    except (OSError, ProcessLookupError):
                        pass
                self._json({'ok': True})
                return
            if method == 'GET' and parts[2:] == ['logs']:
                follow = query.get('follow', '0') == '1'
                self.send_response(200)
                self.send_header('Content-Type', 'text/plain; charset=utf-8')
                self.end_headers()
                done = lambda: self._exec_rc(job_id) is not None
                for line in log_lib.tail_logs(
                        os.path.join(d, 'rank.log'), follow=follow,
                        stop_condition=done):
                    self.wfile.write(line.encode('utf-8', errors='replace'))
                    self.wfile.flush()
                return
        self._json({'error': 'bad exec route'}, code=404)

    def _exec_rc(self, job_id: int) -> Optional[int]:
        assert STATE is not None
        with STATE.execs_lock:
            entry = STATE.execs.get(job_id)
        if entry is not None:
            return entry['rc']
        # Agent restarted: fall back to the rc file.
        rc_path = os.path.join(STATE.exec_dir(job_id), 'rc')
        try:
            with open(rc_path, 'r', encoding='utf-8') as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None


def main() -> None:
    global STATE
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, default=constants.AGENT_PORT)
    parser.add_argument('--home', default=constants.SKY_REMOTE_HOME)
    parser.add_argument('--cluster', default='unknown')
    parser.add_argument('--head', action='store_true')
    parser.add_argument('--bind', default='0.0.0.0')
    args = parser.parse_args()

    STATE = AgentState(args.home, args.cluster, args.head)
    threading.Thread(target=_event_loop, args=(STATE,), daemon=True).start()
    server = ThreadingHTTPServer((args.bind, args.port), Handler)
    print(f'agent listening on {args.bind}:{args.port} '
          f'(head={args.head}, home={STATE.home})', flush=True)
    server.serve_forever()


if __name__ == '__main__':
    main()
