"""Job driver: gang-execute one job's rank scripts on every host agent.

This is the TPU-native replacement for the reference's generated Ray
driver (sky/backends/task_codegen.py:301 RayCodeGen — placement group
STRICT_SPREAD + get_or_fail kill-all-on-failure). A TPU slice is
already gang-allocated by the TPU API, so "gang scheduling" reduces
to: start the rank script on every host agent, watch all of them, and
cancel everything if any rank fails (all-or-nothing semantics,
reference task_codegen.py:363-411).

Log fan-in: one thread per rank streams that host's log into
`<job_log_dir>/rank-<i>.log` and the combined `run.log` (rank-prefixed
when num_ranks > 1) — the reference's per-rank `{rank}-{node}.log`
contract (task_codegen.py:640-650).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import log_lib

_POLL_SECONDS = 1.0


class RankExec:

    def __init__(self, host: Dict[str, Any], job_id: int,
                 secret: Optional[str] = None) -> None:
        self.host = host          # {'addr': 'ip:port', 'rank': int, ...}
        self.rank = int(host['rank'])
        self.job_id = job_id
        self.base = f'http://{host["addr"]}'
        # All agents of one cluster share the head's secret.
        self.headers = {'X-Agent-Token': secret} if secret else {}
        self.rc: Optional[int] = None

    def start(self, script: str, env: Dict[str, str],
              cwd: Optional[str]) -> None:
        resp = requests.post(f'{self.base}/exec', json={
            'job_id': self.job_id,
            'script': script,
            'env': env,
            'cwd': cwd,
        }, timeout=30, headers=self.headers)
        resp.raise_for_status()

    def poll(self) -> Optional[int]:
        if self.rc is not None:
            return self.rc
        try:
            resp = requests.get(f'{self.base}/exec/{self.job_id}/status',
                                timeout=10, headers=self.headers)
            resp.raise_for_status()
            data = resp.json()
            if not data['running']:
                self.rc = data['rc'] if data['rc'] is not None else 255
        except requests.RequestException:
            # Host agent unreachable: count as failure after grace.
            self.rc = 254
        return self.rc

    def cancel(self) -> None:
        try:
            requests.post(f'{self.base}/exec/{self.job_id}/cancel',
                          timeout=10, headers=self.headers)
        except requests.RequestException:
            pass

    def stream_logs(self, rank_log_path: str, combined, prefix: str,
                    lock: threading.Lock) -> None:
        os.makedirs(os.path.dirname(rank_log_path), exist_ok=True)
        try:
            with requests.get(f'{self.base}/exec/{self.job_id}/logs',
                              params={'follow': '1'}, stream=True,
                              timeout=(30, None),
                              headers=self.headers) as resp:
                with open(rank_log_path, 'ab') as rank_file:
                    for raw in resp.iter_lines(decode_unicode=False):
                        rank_file.write(raw + b'\n')
                        rank_file.flush()
                        with lock:
                            if prefix:
                                combined.write(prefix.encode())
                            combined.write(raw + b'\n')
                            combined.flush()
        except requests.RequestException as e:
            with lock:
                combined.write(
                    f'[driver] log stream for rank {self.rank} ended: '
                    f'{e}\n'.encode())
                combined.flush()


def run_job(home: str, job_id: int) -> job_lib.JobStatus:
    jobs = job_lib.JobTable(home)
    job = jobs.get_job(job_id)
    assert job is not None, f'job {job_id} not found'
    spec = job['spec']
    log_dir = job['log_dir']
    os.makedirs(log_dir, exist_ok=True)

    hosts: List[Dict[str, Any]] = spec['hosts']
    script: str = spec['script']
    base_env: Dict[str, str] = spec.get('env', {})
    per_rank_env: List[Dict[str, str]] = spec.get('per_rank_env',
                                                  [{} for _ in hosts])
    cwd = spec.get('cwd')

    from skypilot_tpu.agent import agent as agent_lib
    secret = agent_lib.read_secret(home)
    execs = [RankExec(h, job_id, secret) for h in hosts]
    combined_path = os.path.join(log_dir, 'run.log')
    combined = open(combined_path, 'ab', buffering=0)
    lock = threading.Lock()

    cancelled = threading.Event()

    def handle_term(signum, frame):  # noqa: ARG001
        cancelled.set()

    signal.signal(signal.SIGTERM, handle_term)

    jobs.set_status(job_id, job_lib.JobStatus.RUNNING)
    final = job_lib.JobStatus.SUCCEEDED
    try:
        # Start all ranks (any start failure → nothing proceeds).
        for ex, extra in zip(execs, per_rank_env):
            env = dict(base_env)
            env.update(extra)
            try:
                ex.start(script, env, cwd)
            except requests.RequestException as e:
                detail = ''
                resp = getattr(e, 'response', None)
                if resp is not None:
                    detail = f' ({resp.text[:500]})'
                with lock:
                    combined.write(
                        f'[driver] failed to start rank {ex.rank}: '
                        f'{e}{detail}\n'.encode())
                for other in execs:
                    other.cancel()
                final = job_lib.JobStatus.FAILED
                break

        if final != job_lib.JobStatus.SUCCEEDED:
            return final  # finally block records the status

        # Fan in logs.
        streamers = []
        for ex in execs:
            prefix = f'(rank{ex.rank}) ' if len(execs) > 1 else ''
            t = threading.Thread(
                target=ex.stream_logs,
                args=(os.path.join(log_dir, f'rank-{ex.rank}.log'),
                      combined, prefix, lock),
                daemon=True)
            t.start()
            streamers.append(t)

        # Watch all ranks; kill-all-on-any-failure.
        pending = set(execs)
        while pending:
            if cancelled.is_set():
                for ex in execs:
                    ex.cancel()
                final = job_lib.JobStatus.CANCELLED
                break
            done = {ex for ex in pending if ex.poll() is not None}
            for ex in done:
                with lock:
                    combined.write(
                        f'[driver] rank {ex.rank} exited rc={ex.rc}\n'
                        .encode())
                if ex.rc != 0:
                    # First terminal cause wins: a typed trainer exit
                    # (graceful preemption checkpoint, watchdog abort
                    # — train_guard.py) maps to its typed status so
                    # the managed-jobs controller recovers instead of
                    # failing; the SIGTERM rcs of the siblings this
                    # kill-all cancels must not overwrite it.
                    if final == job_lib.JobStatus.SUCCEEDED:
                        typed = job_lib.status_for_exit_code(ex.rc)
                        final = typed or job_lib.JobStatus.FAILED
                        if typed is not None:
                            with lock:
                                combined.write(
                                    f'[driver] rank {ex.rank} exit '
                                    f'code {ex.rc} is typed: job '
                                    f'status {typed.value}\n'.encode())
                    for other in execs:
                        if other is not ex and other.poll() is None:
                            other.cancel()
            pending -= done
            if pending:
                time.sleep(_POLL_SECONDS)

        for t in streamers:
            t.join(timeout=10)
        return final
    finally:
        final = _finish(jobs, job_id, log_dir, final, combined)


def _finish(jobs: job_lib.JobTable, job_id: int, log_dir: str,
            status: job_lib.JobStatus, combined) -> job_lib.JobStatus:
    with open(os.path.join(log_dir, 'driver_status'), 'w',
              encoding='utf-8') as f:
        f.write(status.value)
    jobs.set_status(job_id, status)
    combined.write(f'[driver] job {job_id} finished: {status.value}\n'
                   .encode())
    combined.close()
    _ship_logs(os.path.dirname(os.path.dirname(log_dir)), job_id, log_dir)
    return status


def _ship_logs(home: str, job_id: int, log_dir: str) -> None:
    """External log shipping (reference: sky/logs/__init__.py:11-21 —
    fluentbit/gcp aggregators): when the cluster was provisioned with
    `logs.store` configured, every finished job's log dir is shipped to
    `<store>/<cluster>/<job_id>/`. Bucket URLs use the cloud CLI; plain
    paths copy locally (the e2e substrate)."""
    store_path = os.path.join(home, 'log_store')
    try:
        with open(store_path, 'r', encoding='utf-8') as f:
            store = f.read().strip()
    except OSError:
        return
    if not store:
        return
    try:
        with open(os.path.join(home, 'cluster_name'), 'r',
                  encoding='utf-8') as f:
            cluster = f.read().strip() or 'cluster'
    except OSError:
        cluster = os.path.basename(home.rstrip('/')) or 'cluster'
    dest = f'{store.rstrip("/")}/{cluster}/{job_id}'
    import shlex
    import subprocess
    q = shlex.quote
    if store.startswith('gs://'):
        cmd = f'gcloud storage rsync -r {q(log_dir)} {q(dest)}'
    elif store.startswith('s3://'):
        cmd = f'aws s3 sync {q(log_dir)} {q(dest)}'
    else:
        cmd = f'mkdir -p {q(dest)} && cp -r {q(log_dir)}/. {q(dest)}/'
    proc = subprocess.run(['bash', '-c', cmd], capture_output=True,
                          text=True, check=False)
    if proc.returncode != 0:
        print(f'[driver] log shipping to {dest} failed '
              f'(rc={proc.returncode}): {proc.stderr[-300:]}',
              file=sys.stderr)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--home', required=True)
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args()
    status = run_job(args.home, args.job_id)
    sys.exit(0 if status == job_lib.JobStatus.SUCCEEDED else 1)


if __name__ == '__main__':
    main()
