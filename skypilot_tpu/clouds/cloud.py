"""Cloud ABC: pricing, feasibility, deploy variables, failover zones.

Reference: sky/clouds/cloud.py:143 — each cloud answers the optimizer's
feasibility/price queries and renders provisioner deploy variables.
"""
from __future__ import annotations

import collections
import dataclasses
import enum
import typing
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class CloudCapability(enum.Enum):
    COMPUTE = 'compute'
    STORAGE = 'storage'


class CloudImplementationFeatures(enum.Enum):
    """Features a task may require; clouds declare what they lack.

    Reference: sky/clouds/cloud.py CloudImplementationFeatures.
    """
    STOP = 'stop'
    MULTI_NODE = 'multi_node'
    SPOT_INSTANCE = 'spot_instance'
    AUTOSTOP = 'autostop'
    OPEN_PORTS = 'open_ports'
    STORAGE_MOUNTING = 'storage_mounting'
    IMAGE_ID = 'image_id'
    CUSTOM_DISK_TIER = 'custom_disk_tier'


@dataclasses.dataclass
class Region:
    name: str
    zones: Optional[List['Zone']] = None

    def set_zones(self, zones: List['Zone']) -> 'Region':
        self.zones = zones
        return self


@dataclasses.dataclass
class Zone:
    name: str

    @property
    def region(self) -> str:
        return self.name.rsplit('-', 1)[0]


# Returned by get_feasible_launchable_resources.
ResourcesFeasibility = collections.namedtuple(
    'ResourcesFeasibility', ['resources_list', 'fuzzy_candidate_list'])


class Cloud:
    """Base class for clouds. Subclasses register in CLOUD_REGISTRY."""

    _REPR = 'Cloud'
    OPEN_PORTS_VERSION: int = 1

    # ---- identity ---------------------------------------------------------
    def __repr__(self) -> str:
        return self._REPR

    @classmethod
    def canonical_name(cls) -> str:
        return cls._REPR.lower()

    def is_same_cloud(self, other: Optional['Cloud']) -> bool:
        return other is not None and self.canonical_name() == \
            other.canonical_name()

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Cloud) and self.is_same_cloud(other)

    def __hash__(self) -> int:
        return hash(self.canonical_name())

    # ---- capability / credentials -----------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not)."""
        raise NotImplementedError

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'resources_lib.Resources'
    ) -> Dict[CloudImplementationFeatures, str]:
        return {}

    # ---- regions / zones (failover iteration) -----------------------------
    @classmethod
    def regions_with_offering(cls, instance_type: Optional[str],
                              accelerators: Optional[Dict[str, int]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[Region]:
        raise NotImplementedError

    @classmethod
    def zones_provision_loop(cls, *, region: str,
                             num_nodes: int,
                             instance_type: Optional[str],
                             accelerators: Optional[Dict[str, int]],
                             use_spot: bool) -> Iterator[Optional[List[Zone]]]:
        """Yield zone batches to try within a region (None = region-level)."""
        raise NotImplementedError

    # ---- catalog-backed queries -------------------------------------------
    def validate_region_zone(self, region: Optional[str], zone: Optional[str]):
        raise NotImplementedError

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        raise NotImplementedError

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0

    def spot_zone_economics(
            self, resources: 'resources_lib.Resources'
    ) -> Optional[List[Tuple[str, float, float]]]:
        """(zone, hourly_spot_price, preemption_rate/hour) triples
        for a spot request, sorted by risk-adjusted price — the
        order the optimizer should prefer zones in. None when this
        cloud has no preemption-rate data (the optimizer then scores
        on raw price, the pre-catalog behavior)."""
        del resources
        return None

    @classmethod
    def get_default_instance_type(cls, cpus: Optional[str] = None,
                                  memory: Optional[str] = None
                                  ) -> Optional[str]:
        raise NotImplementedError

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        raise NotImplementedError

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> ResourcesFeasibility:
        """Concrete launchable candidates for a (possibly vague) request.

        Reference: sky/clouds/cloud.py:461.
        """
        raise NotImplementedError

    # ---- provisioner hand-off ---------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: Region,
            zones: Optional[List[Zone]],
            num_nodes: int) -> Dict[str, Any]:
        """Variables consumed by the provisioner / cluster template.

        Reference: sky/clouds/cloud.py:323.
        """
        raise NotImplementedError

    @classmethod
    def provisioner_module(cls) -> str:
        """Python module under skypilot_tpu.provision implementing this cloud."""
        return cls.canonical_name()

    # ---- misc -------------------------------------------------------------
    @classmethod
    def max_cluster_name_length(cls) -> Optional[int]:
        return None

    def instance_type_exists(self, instance_type: str) -> bool:
        raise NotImplementedError
