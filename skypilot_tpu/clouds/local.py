"""Local cloud: process-per-host fake for tests, dev, and CI.

Plays the role the reference fills with `enable_all_clouds` fixtures +
kind clusters (SURVEY §4): a fully functional cloud whose "hosts" are
local directories + processes, so the whole launch pipeline (optimizer
→ provisioner → agent bootstrap → gang exec) runs end-to-end with no
cloud account. Also emulates TPU slices: a `tpu-v5e-16` on Local
provisions `num_hosts` local "host" sandboxes so multi-host gang
execution is exercised for real.
"""
from __future__ import annotations

import multiprocessing
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

import psutil

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import tpu_utils
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

_REGION = 'local'
_ZONE = 'local-a'


@CLOUD_REGISTRY.register()
class Local(cloud.Cloud):
    _REPR = 'Local'

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        return True, None

    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]):
        if region is not None and region != _REGION:
            raise ValueError(f'Local cloud has a single region {_REGION!r}.')
        if zone is not None and zone != _ZONE:
            raise ValueError(f'Local cloud has a single zone {_ZONE!r}.')
        return region, zone

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        return 0.0

    @classmethod
    def get_default_instance_type(cls, cpus: Optional[str] = None,
                                  memory: Optional[str] = None
                                  ) -> Optional[str]:
        return 'local'

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        return (float(multiprocessing.cpu_count()),
                psutil.virtual_memory().total / (1024 ** 3))

    def instance_type_exists(self, instance_type: str) -> bool:
        return instance_type == 'local'

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> cloud.ResourcesFeasibility:
        del num_nodes
        accs = resources.accelerators
        if accs is not None:
            acc_name = next(iter(accs))
            if not tpu_utils.is_tpu(acc_name):
                return cloud.ResourcesFeasibility([], [])
            # Emulated TPU slice: accepted; hosts become sandboxes.
            return cloud.ResourcesFeasibility([resources.copy(cloud=self)], [])
        return cloud.ResourcesFeasibility(
            [resources.copy(cloud=self, instance_type='local')], [])

    @classmethod
    def regions_with_offering(cls, instance_type: Optional[str],
                              accelerators: Optional[Dict[str, int]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud.Region]:
        del instance_type, accelerators, use_spot
        if region is not None and region != _REGION:
            return []
        if zone is not None and zone != _ZONE:
            return []
        return [cloud.Region(_REGION).set_zones([cloud.Zone(_ZONE)])]

    @classmethod
    def zones_provision_loop(cls, *, region: str, num_nodes: int,
                             instance_type: Optional[str],
                             accelerators: Optional[Dict[str, int]],
                             use_spot: bool
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        del region, num_nodes, instance_type, accelerators, use_spot
        yield [cloud.Zone(_ZONE)]

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: cloud.Region,
            zones: Optional[List[cloud.Zone]],
            num_nodes: int) -> Dict[str, Any]:
        spec = resources.slice_spec
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region.name,
            'zone': zones[0].name if zones else _ZONE,
            'num_nodes': num_nodes,
            'use_spot': resources.use_spot,
            'tpu_vm': spec is not None,
            'tpu_num_hosts': spec.num_hosts if spec is not None else 1,
            'tpu_accelerator_type': (spec.gcp_accelerator_type()
                                     if spec is not None else None),
            'tpu_chips_per_host': (spec.chips_per_host
                                   if spec is not None else 0),
        }
