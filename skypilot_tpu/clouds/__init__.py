"""Cloud implementations. Importing this package registers all clouds."""
from skypilot_tpu.clouds.aws import AWS
from skypilot_tpu.clouds.azure import Azure
from skypilot_tpu.clouds.cloud import (Cloud, CloudImplementationFeatures,
                                       Region, ResourcesFeasibility, Zone)
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.local import Local
from skypilot_tpu.clouds.ssh import SSH

__all__ = [
    'AWS', 'Azure', 'Cloud', 'CloudImplementationFeatures', 'Region',
    'ResourcesFeasibility', 'Zone', 'GCP', 'Kubernetes', 'Local', 'SSH',
]
