"""SSH cloud: bring-your-own machines as a provisioning target.

Reference: sky/ssh_node_pools/ + the `ssh` cloud — machines declared
in `~/.sky-tpu/ssh_node_pools.yaml` become schedulable hosts:

    pools:
      my-pool:
        user: ubuntu
        identity_file: ~/.ssh/id_ed25519
        hosts:
          - 10.0.0.1
          - ip: 10.0.0.2
            user: other
            port: 2222

A "region" is a pool name (`infra: ssh/my-pool`); provisioning
allocates free hosts from the pool (bookkeeping in the state dir) and
bootstraps agents over SSH like any cloud host.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

import yaml

from skypilot_tpu import constants
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

POOLS_PATH = '~/.sky-tpu/ssh_node_pools.yaml'


def load_pools(path: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    path = os.path.expanduser(path or POOLS_PATH)
    if not os.path.exists(path):
        return {}
    with open(path, 'r', encoding='utf-8') as f:
        config = yaml.safe_load(f) or {}
    pools = config.get('pools', config) or {}
    out: Dict[str, Dict[str, Any]] = {}
    for name, pool in pools.items():
        pool = dict(pool or {})
        default_user = pool.get('user', 'root')
        default_key = pool.get('identity_file', '~/.ssh/id_ed25519')
        hosts = []
        for h in pool.get('hosts', []):
            if isinstance(h, str):
                h = {'ip': h}
            hosts.append({
                'ip': h['ip'],
                'user': h.get('user', default_user),
                'identity_file': h.get('identity_file', default_key),
                'port': int(h.get('port', 22)),
            })
        out[name] = {'hosts': hosts}
    return out


@CLOUD_REGISTRY.register()
class SSH(cloud.Cloud):
    _REPR = 'SSH'

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        pools = load_pools()
        if not pools:
            return False, (f'No SSH node pools at {POOLS_PATH}.')
        return True, None

    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]):
        if zone is not None:
            raise ValueError('SSH pools have no zones.')
        if region is not None and region not in load_pools():
            raise ValueError(
                f'SSH pool {region!r} not found; known: '
                f'{sorted(load_pools())}')
        return region, zone

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        return 0.0  # BYO hardware

    @classmethod
    def get_default_instance_type(cls, cpus=None, memory=None):
        return 'ssh-host'

    @classmethod
    def get_vcpus_mem_from_instance_type(cls, instance_type):
        return None, None

    def instance_type_exists(self, instance_type: str) -> bool:
        return instance_type == 'ssh-host'

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> cloud.ResourcesFeasibility:
        if resources.accelerators is not None and \
                not resources.is_tpu_slice:
            return cloud.ResourcesFeasibility([], [])
        pools = load_pools()
        candidates = pools
        if resources.region is not None:
            candidates = {k: v for k, v in pools.items()
                          if k == resources.region}
        for pool in candidates.values():
            if len(pool['hosts']) >= num_nodes:
                return cloud.ResourcesFeasibility(
                    [resources.copy(cloud=self)], [])
        return cloud.ResourcesFeasibility([], [])

    @classmethod
    def regions_with_offering(cls, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        del instance_type, accelerators, use_spot, zone
        pools = load_pools()
        names = [region] if region else sorted(pools)
        return [cloud.Region(n) for n in names if n in pools]

    @classmethod
    def zones_provision_loop(cls, *, region, num_nodes, instance_type,
                             accelerators, use_spot):
        del num_nodes, instance_type, accelerators, use_spot
        yield None

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: cloud.Region,
            zones, num_nodes: int) -> Dict[str, Any]:
        del zones
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'pool': region.name,
            'num_nodes': num_nodes,
            'tpu_vm': False,
            'tpu_num_hosts': 1,
        }
