"""Azure cloud: ARM VMs (GPU/CPU) as a third public cloud.

Reference: sky/clouds/azure.py — the TPU-native build keeps GCP
primary (TPU slices) and adds Azure alongside AWS for the multi-cloud
optimizer story: V100/A100/H100 GPU families and the D/E/F CPU
ladder, spot (Spot VMs with Delete eviction), cross-cloud egress.
Provisioning goes through `provision/azure/` (ARM REST, no SDK).
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.catalog import azure_catalog
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@CLOUD_REGISTRY.register()
class Azure(cloud.Cloud):
    _REPR = 'Azure'

    @classmethod
    def max_cluster_name_length(cls) -> Optional[int]:
        # Resource-group names allow 90 chars but VM computerName is
        # capped at 64; keep hostname-safe parity with AWS.
        return 42

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.azure import arm_api
        if arm_api.load_credentials() is not None:
            return True, None
        return False, ('Azure credentials not found. Set '
                       'AZURE_SUBSCRIPTION_ID/AZURE_TENANT_ID/'
                       'AZURE_CLIENT_ID/AZURE_CLIENT_SECRET or populate '
                       '~/.azure/skypilot.json.')

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        out = {}
        if resources.is_tpu_slice:
            out[cloud.CloudImplementationFeatures.MULTI_NODE] = (
                'TPU slices are GCP-only; Azure offers GPU '
                'instances instead.')
        return out

    # ---- catalog ----------------------------------------------------------
    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]):
        return azure_catalog.validate_region_zone(region, zone)

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        assert resources.instance_type is not None, resources
        return azure_catalog.get_hourly_cost(
            resources.instance_type, resources.use_spot, resources.region,
            resources.zone)

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Tiered internet egress (reference: sky/clouds/azure.py).
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 10240:
            return 0.0875 * num_gigabytes
        if num_gigabytes <= 51200:
            return 0.0875 * 10240 + 0.083 * (num_gigabytes - 10240)
        return (0.0875 * 10240 + 0.083 * 40960 +
                0.07 * (num_gigabytes - 51200))

    @classmethod
    def get_default_instance_type(cls, cpus: Optional[str] = None,
                                  memory: Optional[str] = None
                                  ) -> Optional[str]:
        return azure_catalog.get_default_instance_type(cpus, memory)

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        return azure_catalog.get_vcpus_mem_from_instance_type(instance_type)

    def instance_type_exists(self, instance_type: str) -> bool:
        return azure_catalog.get_vcpus_mem_from_instance_type(
            instance_type)[0] is not None

    # ---- feasibility ------------------------------------------------------
    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> cloud.ResourcesFeasibility:
        del num_nodes
        if resources.is_tpu_slice:
            return cloud.ResourcesFeasibility([], [])
        if resources.instance_type is not None:
            if self.instance_type_exists(resources.instance_type):
                return cloud.ResourcesFeasibility(
                    [resources.copy(cloud=self)], [])
            return cloud.ResourcesFeasibility([], [])
        accs = resources.accelerators
        if accs is None:
            instance_type = azure_catalog.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return cloud.ResourcesFeasibility([], [])
            return cloud.ResourcesFeasibility(
                [resources.copy(cloud=self, instance_type=instance_type)],
                [])
        acc_name, acc_count = next(iter(accs.items()))
        instance_types = azure_catalog.get_instance_type_for_accelerator(
            acc_name, acc_count)
        if not instance_types:
            fuzzy_all = azure_catalog.list_accelerators(
                name_filter=acc_name.split('-')[0], case_sensitive=False)
            fuzzy = sorted(f'{name}:{int(i.accelerator_count)}'
                           for name, infos in fuzzy_all.items()
                           for i in infos[:1])
            return cloud.ResourcesFeasibility([], fuzzy)
        return cloud.ResourcesFeasibility(
            [resources.copy(cloud=self, instance_type=it)
             for it in instance_types], [])

    # ---- failover iteration -----------------------------------------------
    @classmethod
    def regions_with_offering(cls, instance_type: Optional[str],
                              accelerators: Optional[Dict[str, int]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud.Region]:
        # Cheapest-region-first walk order (ties break by name).
        if instance_type is not None:
            region_names = azure_catalog.regions_by_price(
                use_spot, instance_type=instance_type)
        elif accelerators:
            acc_name = next(iter(accelerators))
            region_names = azure_catalog.regions_by_price(
                use_spot, acc_name=acc_name)
        else:
            region_names = azure_catalog.regions_by_price(use_spot)
        out = []
        for r in region_names:
            if region is not None and r != region:
                continue
            if zone is not None:
                zones = [cloud.Zone(zone)]
            else:
                zones = [cloud.Zone(z) for z in
                         azure_catalog.get_zones(
                             r, instance_type=instance_type)] or None
            out.append(cloud.Region(r).set_zones(zones))
        return out

    @classmethod
    def zones_provision_loop(cls, *, region: str, num_nodes: int,
                             instance_type: Optional[str],
                             accelerators: Optional[Dict[str, int]],
                             use_spot: bool
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        # Zone-by-zone (GCP-style): a ZonalAllocationFailed-class error
        # (failover_patterns.py AZURE_PATTERNS, ZONE scope) advances to
        # the region's next zone instead of abandoning the region.
        del num_nodes, accelerators, use_spot
        zones = azure_catalog.get_zones(region,
                                        instance_type=instance_type)
        for z in zones:
            yield [cloud.Zone(z)]
        if not zones:
            yield None  # region-level: ARM picks placement

    # ---- deploy variables -------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: cloud.Region,
            zones: Optional[List[cloud.Zone]],
            num_nodes: int) -> Dict[str, Any]:
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region.name,
            'zone': zones[0].name if zones else None,
            'num_nodes': num_nodes,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'ports': resources.ports,
            'labels': resources.labels or {},
            'image_id': resources.image_id,
            'instance_type': resources.instance_type,
            'accelerators': resources.accelerators or {},
            'tpu_vm': False,
        }
