"""Kubernetes cloud: GKE TPU pod slices + generic CPU pods.

Reference: sky/clouds/kubernetes.py — region == kubeconfig context
(`infra: k8s/<context>`); feasibility is optimistic (the scheduler
owns placement), pricing is zero (BYO cluster).
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import kubeconfig
from skypilot_tpu.utils import tpu_utils
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@CLOUD_REGISTRY.register(aliases=['k8s'])
class Kubernetes(cloud.Cloud):
    _REPR = 'Kubernetes'

    @classmethod
    def max_cluster_name_length(cls) -> Optional[int]:
        return 40  # pod-name suffixes must stay under the 63-char cap

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        contexts = kubeconfig.load_contexts()
        if not contexts:
            return False, 'No kubeconfig contexts found (~/.kube/config).'
        return True, None

    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]):
        if zone is not None:
            raise ValueError('Kubernetes has no zones; use '
                             'infra: k8s/<context>.')
        if region is not None:
            contexts = kubeconfig.load_contexts()
            if contexts and region not in contexts:
                raise ValueError(
                    f'Context {region!r} not in kubeconfig; known: '
                    f'{contexts}')
        return region, zone

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        return 0.0  # BYO cluster

    @classmethod
    def get_default_instance_type(cls, cpus: Optional[str] = None,
                                  memory: Optional[str] = None
                                  ) -> Optional[str]:
        return 'pod'

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        return None, None

    def instance_type_exists(self, instance_type: str) -> bool:
        return instance_type == 'pod'

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> cloud.ResourcesFeasibility:
        del num_nodes
        accs = resources.accelerators
        if accs is not None:
            acc_name = next(iter(accs))
            if not tpu_utils.is_tpu(acc_name):
                return cloud.ResourcesFeasibility([], [])
        return cloud.ResourcesFeasibility([resources.copy(cloud=self)], [])

    @classmethod
    def regions_with_offering(cls, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        del instance_type, accelerators, use_spot, zone
        contexts = kubeconfig.load_contexts()
        if region is not None:
            contexts = [c for c in contexts if c == region]
        return [cloud.Region(c) for c in contexts]

    @classmethod
    def zones_provision_loop(cls, *, region: str, num_nodes: int,
                             instance_type, accelerators, use_spot
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        del num_nodes, instance_type, accelerators, use_spot
        yield None  # context-level provisioning, no zones

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: cloud.Region,
            zones: Optional[List[cloud.Zone]],
            num_nodes: int) -> Dict[str, Any]:
        del zones
        spec = resources.slice_spec
        out: Dict[str, Any] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'context': region.name or None,
            'namespace': None,  # default from kubeconfig
            'num_nodes': num_nodes,
            'image_id': resources.image_id,
            'cpus': resources.cpus.rstrip('+') if resources.cpus else None,
            'memory': (resources.memory.rstrip('+')
                       if resources.memory else None),
            'tpu_vm': spec is not None,
        }
        if spec is not None:
            out.update({
                'tpu_accelerator_type': spec.gcp_accelerator_type(),
                'tpu_topology': resources.accelerator_args.get(
                    'topology', spec.topology_str),
                'tpu_num_hosts': spec.num_hosts,
                'tpu_chips_per_host': spec.chips_per_host,
            })
        return out
