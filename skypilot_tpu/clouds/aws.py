"""AWS cloud: EC2 GPU/Trainium/CPU hosts as a second public cloud.

Reference: sky/clouds/aws.py — the TPU-native build keeps GCP primary
(TPU slices) and adds AWS for the multi-cloud optimizer story: GPU
training/serving families, spot failover, cross-cloud egress costs.
Provisioning goes through `provision/aws/` (SigV4 Query API, no SDK).
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.catalog import aws_catalog
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@CLOUD_REGISTRY.register()
class AWS(cloud.Cloud):
    _REPR = 'AWS'

    @classmethod
    def max_cluster_name_length(cls) -> Optional[int]:
        # Name tag limit is generous; keep parity with the reference's
        # practical bound for hostname-safe names.
        return 50

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.aws import ec2_api
        if ec2_api.load_credentials() is not None:
            return True, None
        return False, ('AWS credentials not found. Set AWS_ACCESS_KEY_ID/'
                       'AWS_SECRET_ACCESS_KEY or populate '
                       '~/.aws/credentials.')

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        out = {}
        if resources.is_tpu_slice:
            out[cloud.CloudImplementationFeatures.MULTI_NODE] = (
                'TPU slices are GCP-only; AWS offers GPU/Trainium '
                'instances instead.')
        return out

    # ---- catalog ----------------------------------------------------------
    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]):
        return aws_catalog.validate_region_zone(region, zone)

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        assert resources.instance_type is not None, resources
        return aws_catalog.get_hourly_cost(
            resources.instance_type, resources.use_spot, resources.region,
            resources.zone)

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Tiered internet egress (reference: sky/clouds/aws.py).
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 10240:
            return 0.09 * num_gigabytes
        if num_gigabytes <= 51200:
            return 0.09 * 10240 + 0.085 * (num_gigabytes - 10240)
        return 0.09 * 10240 + 0.085 * 40960 + 0.07 * (num_gigabytes - 51200)

    @classmethod
    def get_default_instance_type(cls, cpus: Optional[str] = None,
                                  memory: Optional[str] = None
                                  ) -> Optional[str]:
        return aws_catalog.get_default_instance_type(cpus, memory)

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        return aws_catalog.get_vcpus_mem_from_instance_type(instance_type)

    def instance_type_exists(self, instance_type: str) -> bool:
        return aws_catalog.get_vcpus_mem_from_instance_type(
            instance_type)[0] is not None

    # ---- feasibility ------------------------------------------------------
    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> cloud.ResourcesFeasibility:
        del num_nodes
        if resources.is_tpu_slice:
            return cloud.ResourcesFeasibility([], [])
        if resources.instance_type is not None:
            if self.instance_type_exists(resources.instance_type):
                return cloud.ResourcesFeasibility(
                    [resources.copy(cloud=self)], [])
            return cloud.ResourcesFeasibility([], [])
        accs = resources.accelerators
        if accs is None:
            instance_type = aws_catalog.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return cloud.ResourcesFeasibility([], [])
            return cloud.ResourcesFeasibility(
                [resources.copy(cloud=self, instance_type=instance_type)],
                [])
        acc_name, acc_count = next(iter(accs.items()))
        instance_types = aws_catalog.get_instance_type_for_accelerator(
            acc_name, acc_count)
        if not instance_types:
            fuzzy_all = aws_catalog.list_accelerators(
                name_filter=acc_name.split('-')[0], case_sensitive=False)
            fuzzy = sorted(f'{name}:{int(i.accelerator_count)}'
                           for name, infos in fuzzy_all.items()
                           for i in infos[:1])
            return cloud.ResourcesFeasibility([], fuzzy)
        return cloud.ResourcesFeasibility(
            [resources.copy(cloud=self, instance_type=it)
             for it in instance_types], [])

    # ---- failover iteration -----------------------------------------------
    @classmethod
    def regions_with_offering(cls, instance_type: Optional[str],
                              accelerators: Optional[Dict[str, int]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud.Region]:
        # Cheapest-region-first walk order (ties break by name), so
        # failover tries the lowest-cost region that can serve first.
        if instance_type is not None:
            region_names = aws_catalog.regions_by_price(
                use_spot, instance_type=instance_type)
        elif accelerators:
            acc_name = next(iter(accelerators))
            region_names = aws_catalog.regions_by_price(
                use_spot, acc_name=acc_name)
        else:
            region_names = aws_catalog.regions_by_price(use_spot)
        out = []
        for r in region_names:
            if region is not None and r != region:
                continue
            zones = [cloud.Zone(z) for z in
                     aws_catalog.zones_for_instance_type(
                         instance_type, r)] if instance_type else []
            if zone is not None:
                zones = [z for z in zones if z.name == zone]
                if not zones:
                    continue
            out.append(cloud.Region(r).set_zones(zones or None))
        return out

    @classmethod
    def zones_provision_loop(cls, *, region: str, num_nodes: int,
                             instance_type: Optional[str],
                             accelerators: Optional[Dict[str, int]],
                             use_spot: bool
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        del num_nodes, accelerators, use_spot
        zones = (aws_catalog.zones_for_instance_type(instance_type, region)
                 if instance_type else [])
        if not zones:
            yield None  # region-level: EC2 picks the AZ
            return
        for z in zones:
            yield [cloud.Zone(z)]

    # ---- deploy variables -------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: cloud.Region,
            zones: Optional[List[cloud.Zone]],
            num_nodes: int) -> Dict[str, Any]:
        return {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region.name,
            'zone': zones[0].name if zones else None,
            'num_nodes': num_nodes,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'ports': resources.ports,
            'labels': resources.labels or {},
            'image_id': resources.image_id,
            'instance_type': resources.instance_type,
            'accelerators': resources.accelerators or {},
            'tpu_vm': False,
        }
