"""GCP cloud: TPU slices as the native accelerator.

Reference: sky/clouds/gcp.py — but where the reference bolts TPUs onto
a GPU-VM model (pseudo instance type 'TPU-VM', hardcoded host shapes,
`:770-823`), here a TPU slice is the primary launchable unit: the
catalog row carries chips/hosts/ICI topology and the deploy variables
speak the TPU API natively (acceleratorType + topology +
QueuedResources).
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.catalog import gcp_catalog
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils import tpu_utils
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

# Default TPU software (runtime) version per generation, for JAX.
_DEFAULT_RUNTIME_VERSION = {
    'v2': 'tpu-ubuntu2204-base',
    'v3': 'tpu-ubuntu2204-base',
    'v4': 'tpu-ubuntu2204-base',
    'v5e': 'v2-alpha-tpuv5-lite',
    'v5p': 'v2-alpha-tpuv5',
    'v6e': 'v2-alpha-tpuv6e',
}


@CLOUD_REGISTRY.register(default=True)
class GCP(cloud.Cloud):
    _REPR = 'GCP'

    @classmethod
    def max_cluster_name_length(cls) -> Optional[int]:
        return 35

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        # Application-default credentials or gcloud auth.
        adc = os.path.expanduser(
            '~/.config/gcloud/application_default_credentials.json')
        if os.environ.get('GOOGLE_APPLICATION_CREDENTIALS') or \
                os.path.exists(adc):
            return True, None
        return False, ('GCP credentials not found. Run '
                       '`gcloud auth application-default login`.')

    @classmethod
    def unsupported_features_for_resources(
        cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        out = {}
        if resources.is_tpu_slice:
            spec = resources.slice_spec
            assert spec is not None
            if spec.is_pod_slice:
                out[cloud.CloudImplementationFeatures.STOP] = (
                    'Multi-host TPU pod slices cannot be stopped; only '
                    'terminated (the TPU API has no stop for pods).')
        return out

    # ---- catalog ----------------------------------------------------------
    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]):
        return gcp_catalog.validate_region_zone(region, zone)

    def get_hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        # TPU slice pricing covers the hosts (per-chip-hour includes VM).
        if resources.is_tpu_slice:
            acc = resources.tpu_accelerator_name
            return gcp_catalog.get_accelerator_hourly_cost(
                acc, 1, resources.use_spot, resources.region, resources.zone)
        assert resources.instance_type is not None, resources
        return gcp_catalog.get_hourly_cost(
            resources.instance_type, resources.use_spot, resources.region,
            resources.zone)

    def spot_zone_economics(self, resources: 'resources_lib.Resources'):
        # Rate data exists for TPU slices only; spot VMs score on raw
        # price like before.
        if not (resources.use_spot and resources.is_tpu_slice):
            return None
        econ = gcp_catalog.spot_zone_economics(
            resources.tpu_accelerator_name, resources.region,
            resources.zone)
        return econ or None

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Tiered internet egress (reference: sky/clouds/gcp.py egress table).
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 1024:
            return 0.12 * num_gigabytes
        if num_gigabytes <= 10240:
            return 0.12 * 1024 + 0.11 * (num_gigabytes - 1024)
        return 0.12 * 1024 + 0.11 * 9216 + 0.08 * (num_gigabytes - 10240)

    @classmethod
    def get_default_instance_type(cls, cpus: Optional[str] = None,
                                  memory: Optional[str] = None
                                  ) -> Optional[str]:
        return gcp_catalog.get_default_instance_type(cpus, memory)

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type: str
    ) -> Tuple[Optional[float], Optional[float]]:
        return gcp_catalog.get_vcpus_mem_from_instance_type(instance_type)

    def instance_type_exists(self, instance_type: str) -> bool:
        return gcp_catalog.get_vcpus_mem_from_instance_type(
            instance_type)[0] is not None

    # ---- feasibility ------------------------------------------------------
    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources',
            num_nodes: int = 1) -> cloud.ResourcesFeasibility:
        del num_nodes
        accs = resources.accelerators
        if resources.instance_type is not None:
            if self.instance_type_exists(resources.instance_type):
                return cloud.ResourcesFeasibility(
                    [resources.copy(cloud=self)], [])
            return cloud.ResourcesFeasibility([], [])

        if accs is None:
            # CPU-only: pick default instance type for cpus/mem.
            instance_type = gcp_catalog.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return cloud.ResourcesFeasibility([], [])
            return cloud.ResourcesFeasibility(
                [resources.copy(cloud=self, instance_type=instance_type)], [])

        acc_name, acc_count = next(iter(accs.items()))
        if tpu_utils.is_tpu(acc_name):
            zones = gcp_catalog.get_tpu_zones(acc_name)
            if resources.region is not None:
                zones = [z for z in zones
                         if z.rsplit('-', 1)[0] == resources.region]
            if resources.zone is not None:
                zones = [z for z in zones if z == resources.zone]
            if not zones:
                fuzzy = self._fuzzy_tpu_candidates(acc_name)
                return cloud.ResourcesFeasibility([], fuzzy)
            # Slice is launchable as-is; host shape implied.
            return cloud.ResourcesFeasibility(
                [resources.copy(cloud=self)], [])

        # GPU path: find host instance types carrying the accelerator.
        instance_types = gcp_catalog.get_instance_type_for_accelerator(
            acc_name, acc_count)
        if not instance_types:
            fuzzy_all = gcp_catalog.list_accelerators(
                name_filter=acc_name.split('-')[0], case_sensitive=False)
            fuzzy = sorted(f'{name}:{int(i.accelerator_count)}'
                           for name, infos in fuzzy_all.items()
                           for i in infos[:1])
            return cloud.ResourcesFeasibility([], fuzzy)
        return cloud.ResourcesFeasibility(
            [resources.copy(cloud=self, instance_type=it)
             for it in instance_types], [])

    @staticmethod
    def _fuzzy_tpu_candidates(acc_name: str) -> List[str]:
        parsed = tpu_utils.parse_tpu_name(acc_name)
        if parsed is None:
            return []
        version = parsed[0]
        return [f'tpu-{version}-{s}'
                for s in tpu_utils.standard_slice_sizes(version)]

    # ---- failover iteration -----------------------------------------------
    @classmethod
    def regions_with_offering(cls, instance_type: Optional[str],
                              accelerators: Optional[Dict[str, int]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud.Region]:
        """Regions carrying the offering, CHEAPEST FIRST, with the
        zones the catalog actually lists (no synthesized '-a')."""
        acc_name = next(iter(accelerators)) if accelerators else None
        if acc_name is not None and tpu_utils.is_tpu(acc_name):
            zones = gcp_catalog.get_tpu_zones(acc_name)
        elif acc_name is not None or instance_type is not None:
            zones = gcp_catalog.get_vm_zones(instance_type=instance_type,
                                             acc_name=acc_name)
        else:
            zones = gcp_catalog.get_vm_zones()
        price_order = {
            r: i for i, r in enumerate(gcp_catalog.regions_by_price(
                use_spot, instance_type=instance_type, acc_name=acc_name))}
        by_region: Dict[str, List[cloud.Zone]] = {}
        for z in zones:
            r = z.rsplit('-', 1)[0]
            by_region.setdefault(r, []).append(cloud.Zone(z))
        out = []
        for r, zs in sorted(by_region.items(),
                            key=lambda kv: (price_order.get(kv[0], 1 << 30),
                                            kv[0])):
            if region is not None and r != region:
                continue
            if zone is not None:
                zs = [z for z in zs if z.name == zone]
                if not zs:
                    continue
            out.append(cloud.Region(r).set_zones(zs))
        return out

    @classmethod
    def zones_provision_loop(cls, *, region: str, num_nodes: int,
                             instance_type: Optional[str],
                             accelerators: Optional[Dict[str, int]],
                             use_spot: bool
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        # GCP provisions one zone at a time (reference behavior).
        for r in cls.regions_with_offering(instance_type, accelerators,
                                           use_spot, region, None):
            for z in r.zones or []:
                yield [z]

    # ---- deploy variables -------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: cloud.Region,
            zones: Optional[List[cloud.Zone]],
            num_nodes: int) -> Dict[str, Any]:
        zone = zones[0].name if zones else None
        out: Dict[str, Any] = {
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region.name,
            'zone': zone,
            'num_nodes': num_nodes,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'disk_tier': resources.disk_tier or 'balanced',
            'ports': resources.ports,
            'labels': resources.labels or {},
            'image_id': resources.image_id,
        }
        spec = resources.slice_spec
        if spec is not None:
            args = resources.accelerator_args
            out.update({
                'tpu_vm': True,
                'tpu_version': spec.version,
                'tpu_accelerator_type': spec.gcp_accelerator_type(),
                'tpu_topology': args.get('topology', spec.topology_str),
                'tpu_num_hosts': spec.num_hosts,
                'tpu_chips_per_host': spec.chips_per_host,
                'runtime_version': args.get(
                    'runtime_version', _DEFAULT_RUNTIME_VERSION[spec.version]),
                'tpu_reserved': bool(args.get('reserved', False)),
                'tpu_use_queued_resources': bool(
                    args.get('queued_resources',
                             resources.use_spot or spec.is_pod_slice)),
            })
        else:
            out.update({
                'tpu_vm': False,
                'instance_type': resources.instance_type,
                'accelerators': resources.accelerators or {},
            })
        return out
